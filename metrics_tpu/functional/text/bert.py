"""BERTScore functional (reference: functional/text/bert.py:54-443).

Callable-encoder redesign: instead of hard-wiring HuggingFace ``AutoModel``
plumbing (reference loads a torch model + tokenizer and drives a DataLoader),
the encoder is a user-supplied callable

    ``encoder(sentences: Sequence[str]) -> (embeddings [B, S, D], input_ids [B, S],
    attention_mask [B, S])``

producing HF-style sequences (``[CLS] ... [SEP]`` — positions 0 and the last
attended position are excluded from scoring exactly as the reference does,
helper_embedding_metric.py:35-49). When ``transformers`` is installed and
``model_name_or_path`` is given, a default jit-compiled encoder is built
automatically. All scoring math — token-level cosine matching with optional IDF
weighting — runs in jnp and is jit/shard_map-safe.

Delta vs reference: per-call layer selection (``num_layers``/``all_layers``) is
the encoder's concern here — an encoder can return any representation; the
scoring math is layer-agnostic.
"""
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _input_ids_idf, _tokens_idf
from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

_DEFAULT_MODEL = "roberta-large"

TextEncoder = Callable[[Sequence[str]], Tuple[Array, np.ndarray, np.ndarray]]


def _process_attention_mask_for_special_tokens(attention_mask: np.ndarray) -> np.ndarray:
    """Zero out [CLS] (position 0) and [SEP] (last attended position) per row."""
    mask = attention_mask.astype(np.float32).copy()
    mask[:, 0] = 0
    sep_positions = np.argmax(np.cumsum(mask - 0.1, axis=-1), axis=-1)
    mask[np.arange(mask.shape[0]), sep_positions] = 0
    return mask


def _idf_scale(input_ids: np.ndarray, mask: np.ndarray, idf_map: Optional[Dict[int, float]]) -> np.ndarray:
    """Per-token weights normalized within each sentence (uniform when no idf)."""
    if idf_map is None:
        weights = mask.astype(np.float32)
    else:
        weights = _input_ids_idf(input_ids, idf_map) * mask
    return weights / np.maximum(weights.sum(-1, keepdims=True), 1e-30)


def _bert_score_from_embeddings(
    preds_emb: Array,
    preds_scale: Array,
    target_emb: Array,
    target_scale: Array,
) -> Tuple[Array, Array, Array]:
    """Greedy token matching: (precision, recall, f1) per sample — pure jnp.

    Embeddings must be L2-normalized with masked-out positions zeroed; scales must
    be normalized per sentence. NaN f1 (p + r == 0) maps to 0.
    """
    cos_sim = jnp.einsum("bpd,brd->bpr", preds_emb, target_emb)
    precision = jnp.sum(jnp.max(cos_sim, axis=2) * preds_scale, axis=-1)
    recall = jnp.sum(jnp.max(cos_sim, axis=1) * target_scale, axis=-1)
    denom = precision + recall
    f1 = jnp.where(denom > 0, 2 * precision * recall / jnp.where(denom > 0, denom, 1.0), 0.0)
    return precision, recall, f1


def _prepare_embeddings(
    encoder_output: Tuple[Array, np.ndarray, np.ndarray],
    idf_map: Optional[Dict[int, float]],
) -> Tuple[Array, Array]:
    """L2-normalize, zero special-token positions, build per-token scales."""
    embeddings, input_ids, attention_mask = encoder_output
    mask = _process_attention_mask_for_special_tokens(np.asarray(attention_mask))
    emb = jnp.asarray(embeddings)
    emb = emb / jnp.maximum(jnp.linalg.norm(emb, axis=-1, keepdims=True), 1e-30)
    emb = emb * jnp.asarray(mask)[..., None]
    scale = jnp.asarray(_idf_scale(np.asarray(input_ids), mask, idf_map))
    return emb, scale


def _default_transformers_encoder(model_name_or_path: str, max_length: int = 512) -> TextEncoder:
    """HF-transformers encoder (last hidden state); requires cached weights."""
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`bert_score` with `model_name_or_path` requires `transformers`. Either install it or pass an `encoder`."
        )
    import torch
    from transformers import AutoModel, AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = AutoModel.from_pretrained(model_name_or_path)
    model.eval()

    def encoder(sentences: Sequence[str]) -> Tuple[Array, np.ndarray, np.ndarray]:
        batch = tokenizer(
            list(sentences), padding=True, truncation=True, max_length=max_length, return_tensors="pt"
        )
        with torch.no_grad():
            out = model(batch["input_ids"], batch["attention_mask"]).last_hidden_state
        return (
            jnp.asarray(out.numpy()),
            batch["input_ids"].numpy(),
            batch["attention_mask"].numpy(),
        )

    return encoder


def bert_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    encoder: Optional[TextEncoder] = None,
    model_name_or_path: Optional[str] = None,
    idf: bool = False,
    max_length: int = 512,
    rescale_with_baseline: bool = False,
    baseline: Optional[Sequence[float]] = None,
    return_hash: bool = False,
) -> Dict[str, Union[Array, str]]:
    """BERTScore: token-level greedy cosine matching of contextual embeddings.

    Args:
        preds: predicted sentence(s).
        target: reference sentence(s).
        encoder: callable mapping sentences to ``(embeddings, input_ids,
            attention_mask)``; see module docstring for the contract.
        model_name_or_path: build a default ``transformers`` encoder (requires
            locally cached weights; default ``roberta-large`` when neither
            ``encoder`` nor a name is given).
        idf: weight tokens by inverse document frequency computed on ``target``.
        max_length: tokenizer truncation length for the default encoder.
        rescale_with_baseline: linearly rescale scores with ``baseline``
            (three floats: precision/recall/f1 baselines).
        baseline: the baseline values; required when ``rescale_with_baseline``.
        return_hash: include a config hash in the output dict.

    Returns:
        Dict with per-sentence ``precision``, ``recall``, ``f1`` arrays.
    """
    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [target] if isinstance(target, str) else list(target)
    if len(preds_l) != len(target_l):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, got {len(preds_l)} and {len(target_l)}"
        )
    if encoder is None:
        encoder = _default_transformers_encoder(model_name_or_path or _DEFAULT_MODEL, max_length)

    # target embeddings first: idf statistics are computed on references
    target_output = encoder(target_l)
    idf_map = _tokens_idf(np.asarray(target_output[1])) if idf else None
    t_emb, t_scale = _prepare_embeddings(target_output, idf_map)
    p_emb, p_scale = _prepare_embeddings(encoder(preds_l), idf_map)

    precision, recall, f1 = _bert_score_from_embeddings(p_emb, p_scale, t_emb, t_scale)

    if rescale_with_baseline:
        if baseline is None:
            raise ValueError("`rescale_with_baseline` requires the `baseline` argument (no network access).")
        b = jnp.asarray(baseline, jnp.float32)
        precision = (precision - b[0]) / (1 - b[0])
        recall = (recall - b[1]) / (1 - b[1])
        f1 = (f1 - b[2]) / (1 - b[2])

    output: Dict[str, Union[Array, str]] = {"precision": precision, "recall": recall, "f1": f1}
    if return_hash:
        output["hash"] = f"{model_name_or_path}{'_idf' if idf else '_no-idf'}"
    return output
