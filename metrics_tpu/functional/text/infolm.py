"""InfoLM functional (reference: functional/text/infolm.py:40-635).

Information measures between per-sentence discrete token distributions produced by
a masked language model (Colombo et al., "InfoLM: A New Metric to Evaluate
Summarization & Data2Text Generation").

Callable-encoder redesign: the model interface is a single callable

    ``logits_fn(input_ids [B, S], attention_mask [B, S]) -> logits [B, S, V]``

(the HF ``AutoModelForMaskedLM`` forward, or any equivalent). The distribution
builder masks one position at a time exactly like the reference
(infolm.py:355-404): softmax of the masked position's logits at ``temperature``,
optional IDF weighting, averaged over non-special positions. All measure math is
branchless jnp (``nan_to_num`` like the reference) and jit-safe.
"""
from typing import Callable, Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _input_ids_idf, _tokens_idf
from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

_ALLOWED_INFORMATION_MEASURE = (
    "kl_divergence",
    "alpha_divergence",
    "beta_divergence",
    "ab_divergence",
    "renyi_divergence",
    "l1_distance",
    "l2_distance",
    "l_infinity_distance",
    "fisher_rao_distance",
)

LogitsFn = Callable[[np.ndarray, np.ndarray], Array]


class _InformationMeasure:
    """Dispatcher for the nine InfoLM information measures (jnp, nan→0)."""

    def __init__(self, information_measure: str, alpha: Optional[float] = None, beta: Optional[float] = None) -> None:
        if information_measure not in _ALLOWED_INFORMATION_MEASURE:
            raise ValueError(
                f"Argument `information_measure` expected one of {_ALLOWED_INFORMATION_MEASURE}, "
                f"got {information_measure}."
            )
        self.information_measure = information_measure
        needs_alpha = ("alpha_divergence", "ab_divergence", "renyi_divergence")
        if information_measure in needs_alpha and not isinstance(alpha, float):
            raise ValueError(f"Parameter `alpha` is expected to be defined for {information_measure}.")
        if information_measure in ("beta_divergence", "ab_divergence") and not isinstance(beta, float):
            raise ValueError(f"Parameter `beta` is expected to be defined for {information_measure}.")
        if information_measure == "alpha_divergence" and (not isinstance(alpha, float) or alpha in [0, 1]):
            raise ValueError(
                f"Parameter `alpha` is expected to be float differened from 0 and 1 for {information_measure}."
            )
        if information_measure == "beta_divergence" and (not isinstance(beta, float) or beta in [0, -1]):
            raise ValueError(
                f"Parameter `beta` is expected to be float differened from 0 and -1 for {information_measure}."
            )
        if information_measure == "ab_divergence" and (
            alpha is None or beta is None or 0 in [alpha, beta, alpha + beta]
        ):
            raise ValueError(
                f"Parameters `alpha`, `beta` and their sum are expected to be differened from 0 for "
                f"{information_measure}."
            )
        if information_measure == "renyi_divergence" and (not isinstance(alpha, float) or alpha == 1):
            raise ValueError(f"Parameter `alpha` is expected to be float differened from 1 for {information_measure}.")
        self.alpha = alpha or 0.0
        self.beta = beta or 0.0

    def __call__(self, preds_distribution: Array, target_distribution: Array) -> Array:
        fn = getattr(self, f"_calculate_{self.information_measure}")
        return jnp.nan_to_num(fn(preds_distribution, target_distribution))

    @staticmethod
    def _calculate_kl_divergence(p: Array, t: Array) -> Array:
        return jnp.sum(t * jnp.log(p / t), axis=-1)

    def _calculate_alpha_divergence(self, p: Array, t: Array) -> Array:
        denom = self.alpha * (self.alpha - 1)
        return (1 - jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / denom

    def _calculate_ab_divergence(self, p: Array, t: Array) -> Array:
        a = jnp.log(jnp.sum(t ** (self.beta + self.alpha), axis=-1)) / (self.beta * (self.beta + self.alpha))
        b = jnp.log(jnp.sum(p ** (self.beta + self.alpha), axis=-1)) / (self.alpha * (self.beta + self.alpha))
        c = jnp.log(jnp.sum(t**self.alpha * p**self.beta, axis=-1)) / (self.alpha * self.beta)
        return a + b - c

    def _calculate_beta_divergence(self, p: Array, t: Array) -> Array:
        self.alpha = 1.0
        return self._calculate_ab_divergence(p, t)

    def _calculate_renyi_divergence(self, p: Array, t: Array) -> Array:
        return jnp.log(jnp.sum(t**self.alpha * p ** (1 - self.alpha), axis=-1)) / (self.alpha - 1)

    @staticmethod
    def _calculate_l1_distance(p: Array, t: Array) -> Array:
        return jnp.sum(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_l2_distance(p: Array, t: Array) -> Array:
        return jnp.sqrt(jnp.sum((t - p) ** 2, axis=-1))

    @staticmethod
    def _calculate_l_infinity_distance(p: Array, t: Array) -> Array:
        return jnp.max(jnp.abs(t - p), axis=-1)

    @staticmethod
    def _calculate_fisher_rao_distance(p: Array, t: Array) -> Array:
        return 2 * jnp.arccos(jnp.clip(jnp.sqrt(p * t).sum(-1), 0, 1))


def masked_lm_distribution(
    input_ids: np.ndarray,
    attention_mask: np.ndarray,
    logits_fn: LogitsFn,
    special_tokens_map: Dict[str, int],
    temperature: float = 0.25,
    idf_weights: Optional[np.ndarray] = None,
) -> Array:
    """Per-sentence discrete distribution over the vocabulary (reference :355-404).

    Masks each position in turn, reads the masked position's softmax at
    ``temperature``, zeroes special-token positions (pad/sep/cls) and averages
    (IDF-weighted when ``idf_weights`` given).
    """
    input_ids = np.asarray(input_ids)
    seq_len = input_ids.shape[1]
    token_mask = ~(
        (input_ids == special_tokens_map["pad_token_id"])
        | (input_ids == special_tokens_map["sep_token_id"])
        | (input_ids == special_tokens_map["cls_token_id"])
    )
    per_position = []
    for mask_idx in range(seq_len):
        masked = input_ids.copy()
        masked[:, mask_idx] = special_tokens_map["mask_token_id"]
        logits = jnp.asarray(logits_fn(masked, attention_mask))[:, mask_idx, :]
        prob = jax.nn.softmax(logits / temperature, axis=-1)
        if idf_weights is not None:
            prob = prob * jnp.asarray(idf_weights)[:, mask_idx, None]
        per_position.append(prob)
    stacked = jnp.stack(per_position, axis=1)  # [B, S, V]
    stacked = stacked * jnp.asarray(token_mask, stacked.dtype)[..., None]
    if idf_weights is not None:
        denom = jnp.sum(jnp.asarray(token_mask) * jnp.asarray(idf_weights), axis=1)
    else:
        denom = jnp.sum(jnp.asarray(token_mask, stacked.dtype), axis=1)
    return stacked.sum(axis=1) / denom[:, None]


def _load_transformers_mlm(model_name_or_path: str):
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`infolm` with `model_name_or_path` requires `transformers`. Either install it or pass `logits_fn` "
            "+ `tokenizer_fn` + `special_tokens_map`."
        )
    import torch
    from transformers import AutoModelForMaskedLM, AutoTokenizer

    tokenizer = AutoTokenizer.from_pretrained(model_name_or_path)
    model = AutoModelForMaskedLM.from_pretrained(model_name_or_path)
    model.eval()

    def logits_fn(input_ids: np.ndarray, attention_mask: np.ndarray) -> Array:
        with torch.no_grad():
            out = model(torch.tensor(input_ids), torch.tensor(attention_mask)).logits
        return jnp.asarray(out.numpy())

    def tokenizer_fn(sentences: Sequence[str], max_length: int) -> Tuple[np.ndarray, np.ndarray]:
        batch = tokenizer(
            list(sentences), padding="max_length", max_length=max_length, truncation=True, return_tensors="np"
        )
        return batch["input_ids"], batch["attention_mask"]

    special = {
        "mask_token_id": tokenizer.mask_token_id,
        "pad_token_id": tokenizer.pad_token_id,
        "sep_token_id": tokenizer.sep_token_id,
        "cls_token_id": tokenizer.cls_token_id,
    }
    return logits_fn, tokenizer_fn, special


def infolm(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str]],
    model_name_or_path: str = "bert-base-uncased",
    temperature: float = 0.25,
    information_measure: str = "kl_divergence",
    idf: bool = True,
    alpha: Optional[float] = None,
    beta: Optional[float] = None,
    max_length: Optional[int] = None,
    return_sentence_level_score: bool = False,
    logits_fn: Optional[LogitsFn] = None,
    tokenizer_fn: Optional[Callable[[Sequence[str], int], Tuple[np.ndarray, np.ndarray]]] = None,
    special_tokens_map: Optional[Dict[str, int]] = None,
) -> Union[Array, Tuple[Array, Array]]:
    """InfoLM: information measure between masked-LM token distributions.

    Args:
        preds: hypothesis corpus.
        target: reference corpus.
        model_name_or_path: HF masked-LM to load when no ``logits_fn`` is given.
        temperature: softmax calibration temperature.
        information_measure: one of the nine supported measures.
        idf: weight positions by inverse document frequency (computed on ``target``).
        alpha: parameter for alpha/AB/Rényi divergences.
        beta: parameter for beta/AB divergences.
        max_length: tokenizer pad/truncation length (default 512).
        return_sentence_level_score: also return per-sentence values.
        logits_fn: custom masked-LM forward ``(input_ids, attention_mask) -> logits``.
        tokenizer_fn: custom ``(sentences, max_length) -> (input_ids, attention_mask)``.
        special_tokens_map: ids for ``mask/pad/sep/cls`` tokens (required with
            ``logits_fn``).
    """
    if temperature <= 0:
        raise ValueError(f"Argument `temperature` expected to be a positive number, got {temperature}")
    measure = _InformationMeasure(information_measure, alpha, beta)
    max_length = max_length or 512

    if logits_fn is None:
        logits_fn, tokenizer_fn, special_tokens_map = _load_transformers_mlm(model_name_or_path)
    if tokenizer_fn is None or special_tokens_map is None:
        raise ValueError("`logits_fn` requires `tokenizer_fn` and `special_tokens_map` to be provided as well.")

    preds_l = [preds] if isinstance(preds, str) else list(preds)
    target_l = [target] if isinstance(target, str) else list(target)
    if len(preds_l) != len(target_l):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, got {len(preds_l)} and {len(target_l)}"
        )

    p_ids, p_mask = tokenizer_fn(preds_l, max_length)
    t_ids, t_mask = tokenizer_fn(target_l, max_length)

    p_idf = t_idf = None
    if idf:
        idf_map = _tokens_idf(np.asarray(t_ids))
        p_idf = _input_ids_idf(np.asarray(p_ids), idf_map)
        t_idf = _input_ids_idf(np.asarray(t_ids), idf_map)

    preds_distribution = masked_lm_distribution(p_ids, p_mask, logits_fn, special_tokens_map, temperature, p_idf)
    target_distribution = masked_lm_distribution(t_ids, t_mask, logits_fn, special_tokens_map, temperature, t_idf)

    per_sentence = measure(preds_distribution, target_distribution)
    score = per_sentence.mean().astype(jnp.float32)
    if return_sentence_level_score:
        return score, per_sentence.astype(jnp.float32)
    return score
