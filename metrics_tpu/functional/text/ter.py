"""Translation Edit Rate functional (reference: functional/text/ter.py:57-586).

Implements the Tercom algorithm per the published sacrebleu spec: beam-pruned
Levenshtein with an operation trace, greedy shift search with Tercom's candidate
ranking, and the Tercom normalization/tokenization rules. Host-side; only the two
accumulated scalars (total edits, total average reference length) are device state.

Design deltas vs the reference implementation:
- no trie row-cache (`_LevenshteinEditDistance._add_cache`, helper.py:212-246) —
  memoization here is a per-sentence dict keyed by the full hypothesis tuple, which
  is simpler and semantically identical for sentences shorter than the 25-token
  beam (beyond it the reference's cache can leak wider-than-beam rows between
  calls; this implementation always applies the beam consistently);
- the quirk that each reference is scored as hypothesis against the prediction
  (reference ter.py:437 calls ``_translation_edit_rate(tgt_words, pred_words)``)
  is preserved for output parity.
"""
import math
import re
from functools import lru_cache
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _validate_text_inputs

# Tercom limits
_MAX_SHIFT_SIZE = 10
_MAX_SHIFT_DIST = 50
_BEAM_WIDTH = 25
# sacrebleu limit
_MAX_SHIFT_CANDIDATES = 1000
_INF = int(1e16)

# edit ops (trace symbols)
_NOTHING, _SUB, _INS, _DEL, _UNDEF = 0, 1, 2, 3, 4


class _TercomTokenizer:
    """Tercom normalizer/tokenizer (spec: tercom Normalizer.java via sacrebleu)."""

    _ASIAN_PUNCTUATION = r"([、。〈-】〔-〟｡-･・])"
    _FULL_WIDTH_PUNCTUATION = r"([．，？：；！＂（）])"

    def __init__(
        self,
        normalize: bool = False,
        no_punctuation: bool = False,
        lowercase: bool = True,
        asian_support: bool = False,
    ) -> None:
        self.normalize = normalize
        self.no_punctuation = no_punctuation
        self.lowercase = lowercase
        self.asian_support = asian_support

    @lru_cache(maxsize=2**16)
    def __call__(self, sentence: str) -> str:
        if not sentence:
            return ""
        if self.lowercase:
            sentence = sentence.lower()
        if self.normalize:
            sentence = self._normalize_general_and_western(sentence)
            if self.asian_support:
                sentence = self._normalize_asian(sentence)
        if self.no_punctuation:
            sentence = self._remove_punct(sentence)
            if self.asian_support:
                sentence = self._remove_asian_punct(sentence)
        return " ".join(sentence.split())

    @staticmethod
    def _normalize_general_and_western(sentence: str) -> str:
        sentence = f" {sentence} "
        rules = [
            (r"\n-", ""),
            (r"\n", " "),
            (r"&quot;", '"'),
            (r"&amp;", "&"),
            (r"&lt;", "<"),
            (r"&gt;", ">"),
            (r"([{-~[-` -&(-+:-@/])", r" \1 "),
            (r"'s ", r" 's "),
            (r"'s$", r" 's"),
            (r"([^0-9])([\.,])", r"\1 \2 "),
            (r"([\.,])([^0-9])", r" \1 \2"),
            (r"([0-9])(-)", r"\1 \2 "),
        ]
        for pattern, replacement in rules:
            sentence = re.sub(pattern, replacement, sentence)
        return sentence

    @classmethod
    def _normalize_asian(cls, sentence: str) -> str:
        sentence = re.sub(r"([一-鿿㐀-䶿])", r" \1 ", sentence)
        sentence = re.sub(r"([㇀-㇯⺀-⻿])", r" \1 ", sentence)
        sentence = re.sub(r"([㌀-㏿豈-﫿︰-﹏])", r" \1 ", sentence)
        sentence = re.sub(r"([㈀-㼢])", r" \1 ", sentence)
        sentence = re.sub(r"(^|^[぀-ゟ])([぀-ゟ]+)(?=$|^[぀-ゟ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[゠-ヿ])([゠-ヿ]+)(?=$|^[゠-ヿ])", r"\1 \2 ", sentence)
        sentence = re.sub(r"(^|^[ㇰ-ㇿ])([ㇰ-ㇿ]+)(?=$|^[ㇰ-ㇿ])", r"\1 \2 ", sentence)
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r" \1 ", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r" \1 ", sentence)

    @staticmethod
    def _remove_punct(sentence: str) -> str:
        return re.sub(r"[\.,\?:;!\"\(\)]", "", sentence)

    @classmethod
    def _remove_asian_punct(cls, sentence: str) -> str:
        sentence = re.sub(cls._ASIAN_PUNCTUATION, r"", sentence)
        return re.sub(cls._FULL_WIDTH_PUNCTUATION, r"", sentence)


def _beam_levenshtein(pred: Tuple[str, ...], ref: Tuple[str, ...]) -> Tuple[int, Tuple[int, ...]]:
    """Beam-pruned Levenshtein with trace, Tercom op preference (no-op/sub > del > ins).

    Returns (distance, trace-of-ops rewriting ``pred`` into ``ref``); the first row
    is insertions of ``ref``, the first column deletions of ``pred``.
    """
    n, m = len(pred), len(ref)
    # cost/op matrices, rows 0..n, cols 0..m
    cost = [[_INF] * (m + 1) for _ in range(n + 1)]
    op = [[_UNDEF] * (m + 1) for _ in range(n + 1)]
    for j in range(m + 1):
        cost[0][j] = j
        op[0][j] = _INS
    length_ratio = m / n if pred else 1.0
    beam = math.ceil(length_ratio / 2 + _BEAM_WIDTH) if length_ratio / 2 > _BEAM_WIDTH else _BEAM_WIDTH

    for i in range(1, n + 1):
        pseudo_diag = math.floor(i * length_ratio)
        min_j = max(0, pseudo_diag - beam)
        max_j = m + 1 if i == n else min(m + 1, pseudo_diag + beam)
        row, prev = cost[i], cost[i - 1]
        oprow = op[i]
        for j in range(min_j, max_j):
            if j == 0:
                row[0] = prev[0] + 1
                oprow[0] = _DEL
                continue
            if pred[i - 1] == ref[j - 1]:
                sub_cost, sub_op = prev[j - 1], _NOTHING
            else:
                sub_cost, sub_op = prev[j - 1] + 1, _SUB
            best_cost, best_op = row[j], oprow[j]
            for c, o in ((sub_cost, sub_op), (prev[j] + 1, _DEL), (row[j - 1] + 1, _INS)):
                if best_cost > c:
                    best_cost, best_op = c, o
            row[j], oprow[j] = best_cost, best_op

    # backtrack
    trace: List[int] = []
    i, j = n, m
    while i > 0 or j > 0:
        o = op[i][j]
        trace.append(o)
        if o in (_SUB, _NOTHING):
            i -= 1
            j -= 1
        elif o == _INS:
            j -= 1
        elif o == _DEL:
            i -= 1
        else:  # undefined — outside beam; cannot happen for reachable optimum
            raise RuntimeError("TER backtrack left the beam")
    trace.reverse()
    return cost[n][m], tuple(trace)


def _flip_trace(trace: Tuple[int, ...]) -> Tuple[int, ...]:
    """Recipe for rewriting b->a from a->b: swap insertions and deletions."""
    swap = {_INS: _DEL, _DEL: _INS}
    return tuple(swap.get(o, o) for o in trace)


def _trace_to_alignment(trace: Tuple[int, ...]) -> Tuple[Dict[int, int], List[int], List[int]]:
    """Alignment map ref_pos -> hyp_pos plus per-position error flags."""
    ref_pos = hyp_pos = -1
    ref_errors: List[int] = []
    hyp_errors: List[int] = []
    alignments: Dict[int, int] = {}
    for o in trace:
        if o == _NOTHING:
            hyp_pos += 1
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(0)
            hyp_errors.append(0)
        elif o == _SUB:
            hyp_pos += 1
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(1)
            hyp_errors.append(1)
        elif o == _INS:
            hyp_pos += 1
            hyp_errors.append(1)
        elif o == _DEL:
            ref_pos += 1
            alignments[ref_pos] = hyp_pos
            ref_errors.append(1)
        else:
            raise ValueError(f"Unknown operation {o!r}")
    return alignments, ref_errors, hyp_errors


def _find_shifted_pairs(pred_words: List[str], target_words: List[str]) -> Iterator[Tuple[int, int, int]]:
    """All (pred_start, target_start, length) with matching word spans, Tercom limits."""
    for pred_start in range(len(pred_words)):
        for target_start in range(len(target_words)):
            if abs(target_start - pred_start) > _MAX_SHIFT_DIST:
                continue
            for length in range(1, _MAX_SHIFT_SIZE):
                if pred_words[pred_start + length - 1] != target_words[target_start + length - 1]:
                    break
                yield pred_start, target_start, length
                if len(pred_words) == pred_start + length or len(target_words) == target_start + length:
                    break


def _perform_shift(words: List[str], start: int, length: int, target: int) -> List[str]:
    if target < start:
        return words[:target] + words[start : start + length] + words[target:start] + words[start + length :]
    if target > start + length:
        return words[:start] + words[start + length : target] + words[start : start + length] + words[target:]
    return words[:start] + words[start + length : length + target] + words[start : start + length] + words[length + target :]


def _shift_words(
    pred_words: List[str],
    target_words: List[str],
    edit_fn,
    checked_candidates: int,
) -> Tuple[int, List[str], int]:
    """One round of Tercom's greedy shift search; returns (gain, new words, counter)."""
    edit_distance, inverted_trace = edit_fn(tuple(pred_words))
    trace = _flip_trace(inverted_trace)
    alignments, target_errors, pred_errors = _trace_to_alignment(trace)

    best: Optional[Tuple[int, int, int, int, List[str]]] = None
    for pred_start, target_start, length in _find_shifted_pairs(pred_words, target_words):
        # skip unless the hypothesis span is wrong AND the reference span is wrong
        # AND the shift target lies outside the span itself
        if (
            sum(pred_errors[pred_start : pred_start + length]) == 0
            or sum(target_errors[target_start : target_start + length]) == 0
            or pred_start <= alignments[target_start] < pred_start + length
        ):
            continue

        prev_idx = -1
        for offset in range(-1, length):
            if target_start + offset == -1:
                idx = 0
            elif target_start + offset in alignments:
                idx = alignments[target_start + offset] + 1
            else:
                break
            if idx == prev_idx:
                continue
            prev_idx = idx
            shifted_words = _perform_shift(pred_words, pred_start, length, idx)
            # Tercom ranking: gain, then longest, then earliest pred, then earliest target
            candidate = (
                edit_distance - edit_fn(tuple(shifted_words))[0],
                length,
                -pred_start,
                -idx,
                shifted_words,
            )
            checked_candidates += 1
            if not best or candidate > best:
                best = candidate
        if checked_candidates >= _MAX_SHIFT_CANDIDATES:
            break

    if not best:
        return 0, pred_words, checked_candidates
    best_score, _, _, _, shifted_words = best
    return best_score, shifted_words, checked_candidates


def _translation_edit_rate(pred_words: List[str], target_words: List[str]) -> float:
    """Number of edits (shifts + beam-Levenshtein) to match hypothesis to reference."""
    if len(target_words) == 0:
        return 0.0

    ref = tuple(target_words)
    memo: Dict[Tuple[str, ...], Tuple[int, Tuple[int, ...]]] = {}

    def edit_fn(hyp: Tuple[str, ...]) -> Tuple[int, Tuple[int, ...]]:
        if hyp not in memo:
            memo[hyp] = _beam_levenshtein(hyp, ref)
        return memo[hyp]

    num_shifts = 0
    checked_candidates = 0
    input_words = pred_words
    while True:
        delta, new_input_words, checked_candidates = _shift_words(
            input_words, target_words, edit_fn, checked_candidates
        )
        if checked_candidates >= _MAX_SHIFT_CANDIDATES or delta <= 0:
            break
        num_shifts += 1
        input_words = new_input_words

    return float(num_shifts + edit_fn(tuple(input_words))[0])


def _compute_sentence_statistics(
    pred_words: List[str], target_words: List[List[str]]
) -> Tuple[float, float]:
    """Best (lowest) edit count over references + average reference length."""
    tgt_lengths = 0.0
    best_num_edits = 2e16
    for tgt_words in target_words:
        # each reference is scored as hypothesis against the prediction (see module docstring)
        num_edits = _translation_edit_rate(tgt_words, pred_words)
        tgt_lengths += len(tgt_words)
        if num_edits < best_num_edits:
            best_num_edits = num_edits
    avg_tgt_len = tgt_lengths / len(target_words)
    return best_num_edits, avg_tgt_len


def _ter_score_from_statistics(num_edits: float, tgt_length: float) -> float:
    if tgt_length > 0 and num_edits > 0:
        return num_edits / tgt_length
    if tgt_length == 0 and num_edits > 0:
        return 1.0
    return 0.0


def _ter_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    tokenizer: _TercomTokenizer,
    sentence_ter: Optional[List[float]] = None,
) -> Tuple[Array, Array, Optional[List[float]]]:
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[t] if isinstance(t, str) else list(t) for t in target]
    _validate_text_inputs(list(preds), ["x"] * len(target_corpus))  # length check only

    total_num_edits = 0.0
    total_tgt_length = 0.0
    for pred, tgts in zip(preds, target_corpus):
        tgt_words_ = [tokenizer(t.rstrip()).split() for t in tgts]
        pred_words_ = tokenizer(pred.rstrip()).split()
        num_edits, tgt_length = _compute_sentence_statistics(pred_words_, tgt_words_)
        total_num_edits += num_edits
        total_tgt_length += tgt_length
        if sentence_ter is not None:
            sentence_ter.append(_ter_score_from_statistics(num_edits, tgt_length))
    return (
        jnp.asarray(total_num_edits, jnp.float32),
        jnp.asarray(total_tgt_length, jnp.float32),
        sentence_ter,
    )


def _ter_compute(total_num_edits: Array, total_tgt_length: Array) -> Array:
    return jnp.where(
        total_tgt_length > 0,
        total_num_edits / jnp.maximum(total_tgt_length, 1e-30),
        jnp.where(total_num_edits > 0, 1.0, 0.0),
    ).astype(jnp.float32)


def translation_edit_rate(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    normalize: bool = False,
    no_punctuation: bool = False,
    lowercase: bool = True,
    asian_support: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """Translation edit rate (lower = better, 0 = perfect).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> translation_edit_rate(preds, target)
        Array(0.15384616, dtype=float32)
    """
    if not isinstance(normalize, bool):
        raise ValueError(f"Expected argument `normalize` to be of type boolean but got {normalize}.")
    if not isinstance(no_punctuation, bool):
        raise ValueError(f"Expected argument `no_punctuation` to be of type boolean but got {no_punctuation}.")
    if not isinstance(lowercase, bool):
        raise ValueError(f"Expected argument `lowercase` to be of type boolean but got {lowercase}.")
    if not isinstance(asian_support, bool):
        raise ValueError(f"Expected argument `asian_support` to be of type boolean but got {asian_support}.")

    tokenizer = _TercomTokenizer(normalize, no_punctuation, lowercase, asian_support)
    sentence_ter: Optional[List[float]] = [] if return_sentence_level_score else None
    total_num_edits, total_tgt_length, sentence_ter = _ter_update(preds, target, tokenizer, sentence_ter)
    score = _ter_compute(total_num_edits, total_tgt_length)
    if sentence_ter is not None:
        return score, jnp.asarray(sentence_ter, jnp.float32)
    return score
