"""Perplexity functional — fully on-device (reference: functional/text/perplexity.py:69-143).

TPU redesign: the reference materializes a full softmax then indexes and logs
(``probs[:, target].diagonal()``, an O(N²) gather on top of an unnormalized log);
here the per-token negative log-likelihood is ``log_softmax`` + a ``take_along_axis``
gather — one fused XLA kernel, numerically stabler, and jit/grad/shard_map-safe
(the ignore mask is branchless).
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _perplexity_validate(preds: Array, target: Array) -> None:
    if preds.ndim != 3:
        raise ValueError(
            "Input tensor `preds` is expected to have 3 dimensions, [batch_size, seq_len, vocab_size],"
            f" but got {preds.ndim}."
        )
    if target.ndim != 2:
        raise ValueError(
            f"Input tensor `target` is expected to have 2 dimensions, [batch_size, seq_len], but got {target.ndim}."
        )
    if preds.shape[:2] != target.shape:
        raise ValueError(
            "Input tensors `preds` and `target` are expected to have equaling first two dimensions,"
            f" [batch_size, seq_len], but got {preds.shape[:2]} and {target.shape}."
        )
    if not jnp.issubdtype(preds.dtype, jnp.floating):
        raise TypeError(f"Input tensor `preds` is expected to be of floating dtype but got {preds.dtype}.")
    if not jnp.issubdtype(target.dtype, jnp.integer):
        raise TypeError(f"Input tensor `target` is expected to be of integer dtype but got {target.dtype}.")


def _perplexity_update(
    preds: Array, target: Array, ignore_index: Optional[int] = None, validate_args: bool = True
) -> Tuple[Array, Array]:
    if validate_args:
        _perplexity_validate(preds, target)
    logits = preds.reshape(-1, preds.shape[-1]).astype(jnp.float32)
    target = target.reshape(-1)

    if ignore_index is not None:
        mask = target != ignore_index
        target = jnp.where(mask, target, 0)
    else:
        mask = jnp.ones_like(target, dtype=bool)

    log_probs = jax.nn.log_softmax(logits, axis=-1)
    token_nll = -jnp.take_along_axis(log_probs, target[:, None], axis=-1)[:, 0]
    total_log_probs = jnp.sum(jnp.where(mask, token_nll, 0.0))
    count = jnp.sum(mask)
    return total_log_probs, count


def _perplexity_compute(total: Array, count: Array) -> Array:
    return jnp.exp(total / count)


def perplexity(preds: Array, target: Array, ignore_index: Optional[int] = None) -> Array:
    """Perplexity of a language model: ``exp(mean NLL)`` over non-ignored tokens.

    Args:
        preds: logits ``[batch_size, seq_len, vocab_size]`` (normalized internally).
        target: token ids ``[batch_size, seq_len]``.
        ignore_index: target class that does not contribute to the score.

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(22), (2, 8, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(89), (2, 8), 0, 5)
        >>> target = target.at[0, 6:].set(-100)
        >>> perplexity(preds, target, ignore_index=-100)
        Array(5.20..., dtype=float32)
    """
    total, count = _perplexity_update(preds, target, ignore_index)
    return _perplexity_compute(total, count)
