"""Functional text metrics (reference: src/torchmetrics/functional/text/__init__.py)."""
from metrics_tpu.functional.text.bert import bert_score
from metrics_tpu.functional.text.bleu import bleu_score
from metrics_tpu.functional.text.cer import char_error_rate
from metrics_tpu.functional.text.chrf import chrf_score
from metrics_tpu.functional.text.eed import extended_edit_distance
from metrics_tpu.functional.text.infolm import infolm
from metrics_tpu.functional.text.mer import match_error_rate
from metrics_tpu.functional.text.perplexity import perplexity
from metrics_tpu.functional.text.rouge import rouge_score
from metrics_tpu.functional.text.sacre_bleu import sacre_bleu_score
from metrics_tpu.functional.text.squad import squad
from metrics_tpu.functional.text.ter import translation_edit_rate
from metrics_tpu.functional.text.wer import word_error_rate
from metrics_tpu.functional.text.wil import word_information_lost
from metrics_tpu.functional.text.wip import word_information_preserved

__all__ = [
    "bert_score",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "extended_edit_distance",
    "infolm",
    "match_error_rate",
    "perplexity",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "translation_edit_rate",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",
]
