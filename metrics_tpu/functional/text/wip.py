"""Word information preserved functional (reference: functional/text/wip.py:22-90).

Same hit-count sufficient statistics as :mod:`metrics_tpu.functional.text.wil` —
the update is shared; only the final ratio differs (WIP = 1 - WIL).
"""
from typing import Sequence, Union

from jax import Array

from metrics_tpu.functional.text.wil import _wil_update as _wip_update  # noqa: F401  (shared statistics)


def _wip_compute(hits: Array, target_total: Array, preds_total: Array) -> Array:
    return (hits / target_total) * (hits / preds_total)


def word_information_preserved(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Array:
    """Word information preserved: ``(hits/ref_len) * (hits/hyp_len)`` (1 = perfect).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_preserved(preds=preds, target=target)
        Array(0.3472..., dtype=float32)
    """
    hits, target_total, preds_total = _wip_update(preds, target)
    return _wip_compute(hits, target_total, preds_total)
