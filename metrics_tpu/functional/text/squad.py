"""SQuAD exact-match + F1 functional (reference: functional/text/squad.py:41-249).

Host-side string normalization and token-overlap scoring (SQuAD v1 official
formulae); only the three accumulated sufficient statistics are device scalars.
"""
import re
import string
from collections import Counter
from typing import Any, Dict, List, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.prints import rank_zero_warn

SQuAD_FORMAT = {
    "answers": {"answer_start": [1], "text": ["This is a test text"]},
    "context": "This is a test context.",
    "id": "1",
    "question": "Is this a test?",
    "title": "train test",
}

_ARTICLES_RE = re.compile(r"\b(a|an|the)\b")
_PUNCT = set(string.punctuation)


def _normalize_text(s: str) -> str:
    """Lowercase, strip punctuation, articles and extra whitespace (official SQuAD)."""
    s = "".join(ch for ch in s.lower() if ch not in _PUNCT)
    return " ".join(_ARTICLES_RE.sub(" ", s).split())


def _get_tokens(s: str) -> List[str]:
    return _normalize_text(s).split() if s else []


def _f1_score(predicted_answer: str, target_answer: str) -> float:
    target_tokens = _get_tokens(target_answer)
    predicted_tokens = _get_tokens(predicted_answer)
    num_same = sum((Counter(target_tokens) & Counter(predicted_tokens)).values())
    if len(target_tokens) == 0 or len(predicted_tokens) == 0:
        # if either is no-answer, F1 is 1 iff they agree
        return float(target_tokens == predicted_tokens)
    if num_same == 0:
        return 0.0
    precision = num_same / len(predicted_tokens)
    recall = num_same / len(target_tokens)
    return 2 * precision * recall / (precision + recall)


def _exact_match_score(prediction: str, ground_truth: str) -> float:
    return float(_normalize_text(prediction) == _normalize_text(ground_truth))


def _squad_input_check(
    preds: Union[Dict[str, Any], Sequence[Dict[str, Any]]],
    targets: Union[Dict[str, Any], Sequence[Dict[str, Any]]],
) -> Tuple[Dict[str, str], List[Dict[str, Any]]]:
    """Validate SQuAD-format inputs; return ``{id: prediction_text}`` + qas list."""
    if isinstance(preds, dict):
        preds = [preds]
    if isinstance(targets, dict):
        targets = [targets]

    for pred in preds:
        if "prediction_text" not in pred or "id" not in pred:
            raise KeyError(
                "Expected keys in a single prediction are 'prediction_text' and 'id'."
                "Please make sure that 'prediction_text' maps to the answer string and 'id' maps to the key string."
            )
    for target in targets:
        if "answers" not in target or "id" not in target:
            raise KeyError(
                "Expected keys in a single target are 'answers' and 'id'."
                "Please make sure that 'answers' maps to a `SQuAD` format dictionary and 'id' maps to the key string.\n"
                f"SQuAD Format: {SQuAD_FORMAT}"
            )
        if "text" not in target["answers"]:
            raise KeyError(
                "Expected keys in a 'answers' are 'text'."
                "Please make sure that 'answer' maps to a `SQuAD` format dictionary.\n"
                f"SQuAD Format: {SQuAD_FORMAT}"
            )

    preds_dict = {p["id"]: p["prediction_text"] for p in preds}
    qas = [{"id": t["id"], "answers": list(t["answers"]["text"])} for t in targets]
    return preds_dict, qas


def _squad_update(preds: Dict[str, str], qas: List[Dict[str, Any]]) -> Tuple[Array, Array, Array]:
    """Sum of per-question best F1 / best EM over all reference answers, and count."""
    f1 = 0.0
    exact_match = 0.0
    total = 0
    for qa in qas:
        total += 1
        if qa["id"] not in preds:
            rank_zero_warn(f"Unanswered question {qa['id']} will receive score 0.")
            continue
        pred = preds[qa["id"]]
        truths = qa["answers"]
        exact_match += max(_exact_match_score(pred, t) for t in truths)
        f1 += max(_f1_score(pred, t) for t in truths)
    return jnp.asarray(f1, jnp.float32), jnp.asarray(exact_match, jnp.float32), jnp.asarray(total, jnp.int32)


def _squad_compute(f1: Array, exact_match: Array, total: Array) -> Dict[str, Array]:
    return {"exact_match": 100.0 * exact_match / total, "f1": 100.0 * f1 / total}


def squad(
    preds: Union[Dict[str, Any], Sequence[Dict[str, Any]]],
    target: Union[Dict[str, Any], Sequence[Dict[str, Any]]],
) -> Dict[str, Array]:
    """SQuAD v1 exact-match and F1 (both in percent).

    Example:
        >>> preds = [{"prediction_text": "1976", "id": "56e10a3be3433e1400422b22"}]
        >>> target = [{"answers": {"answer_start": [97], "text": ["1976"]}, "id": "56e10a3be3433e1400422b22"}]
        >>> squad(preds, target)
        {'exact_match': Array(100., dtype=float32), 'f1': Array(100., dtype=float32)}
    """
    preds_dict, qas = _squad_input_check(preds, target)
    f1, exact_match, total = _squad_update(preds_dict, qas)
    return _squad_compute(f1, exact_match, total)
