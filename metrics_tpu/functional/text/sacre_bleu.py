"""SacreBLEU functional (reference: functional/text/sacre_bleu.py:85-361).

Implements the published sacrebleu tokenizer spec (mteval-v13a / mteval-v14
international / zh / char) on top of the shared BLEU n-gram statistics. The
``intl`` tokenizer uses the ``regex`` package's Unicode property classes when
available, with a ``unicodedata``-category fallback so no optional dependency is
required.
"""
import re
import unicodedata
from functools import partial
from typing import Optional, Sequence, Union

from jax import Array

from metrics_tpu.functional.text.bleu import _bleu_score_compute, _bleu_score_update
from metrics_tpu.utils.imports import _REGEX_AVAILABLE

AVAILABLE_TOKENIZERS = ("none", "13a", "zh", "intl", "char")

# CJK unicode ranges used by the sacrebleu `zh` tokenizer to isolate Chinese chars
_UCODE_RANGES = (
    ("\u3400", "\u4db5"),  # CJK Unified Ideographs Extension A
    ("\u4e00", "\u9fa5"),  # CJK Unified Ideographs
    ("\u9fa6", "\u9fbb"),
    ("\uf900", "\ufa2d"),  # CJK Compatibility Ideographs
    ("\ufa30", "\ufa6a"),
    ("\ufa70", "\ufad9"),
    ("\U00020000", "\U0002a6d6"),  # CJK Unified Ideographs Extension B
    ("\U0002f800", "\U0002fa1d"),  # CJK Compatibility Supplement
    ("\uff00", "\uffef"),  # full-width ASCII / punctuation, half-width kana
    ("\u2e80", "\u2eff"),  # CJK Radicals Supplement
    ("\u3000", "\u303f"),  # CJK punctuation
    ("\u31c0", "\u31ef"),  # CJK strokes
    ("\u2f00", "\u2fdf"),  # Kangxi Radicals
    ("\u2ff0", "\u2fff"),  # Chinese character structure
    ("\u3100", "\u312f"),  # phonetic symbols
    ("\u31a0", "\u31bf"),
    ("\ufe10", "\ufe1f"),
    ("\ufe30", "\ufe4f"),
    ("\u2600", "\u26ff"),
    ("\u2700", "\u27bf"),
    ("\u3200", "\u32ff"),
    ("\u3300", "\u33ff"),
)

# mteval-v13a language-independent tokenization rules
_13A_RULES = (
    (re.compile(r"([\{-\~\[-\` -\&\(-\+\:-\@\/])"), r" \1 "),
    (re.compile(r"([^0-9])([\.,])"), r"\1 \2 "),
    (re.compile(r"([\.,])([^0-9])"), r" \1 \2"),
    (re.compile(r"([0-9])(-)"), r"\1 \2 "),
)

if _REGEX_AVAILABLE:
    import regex

    _INT_RULES = (
        (regex.compile(r"(\P{N})(\p{P})"), r"\1 \2 "),
        (regex.compile(r"(\p{P})(\P{N})"), r" \1 \2"),
        (regex.compile(r"(\p{S})"), r" \1 "),
    )


def _pair_rule_pass(line: str, first_ok, second_ok, template: str) -> str:
    """One ``s/(X)(Y)/template/g`` pass with regex non-overlapping scan semantics."""
    out = []
    i = 0
    while i < len(line):
        if i + 1 < len(line) and first_ok(line[i]) and second_ok(line[i + 1]):
            out.append(template.format(line[i], line[i + 1]))
            i += 2
        else:
            out.append(line[i])
            i += 1
    return "".join(out)


def _intl_tokenize_fallback(line: str) -> str:
    """mteval-v14 international tokenization via unicodedata categories.

    Replays the three sequential regex passes ``(\\P{N})(\\p{P}) -> 1 2_`` /
    ``(\\p{P})(\\P{N}) -> _1 2`` / ``(\\p{S}) -> _1_`` with faithful
    non-overlapping-match scanning (a per-character context test is NOT
    equivalent for punctuation runs like ``5...``).
    """
    is_n = lambda ch: unicodedata.category(ch).startswith("N")
    is_p = lambda ch: unicodedata.category(ch).startswith("P")
    is_s = lambda ch: unicodedata.category(ch).startswith("S")
    line = _pair_rule_pass(line, lambda c: not is_n(c), is_p, "{0} {1} ")
    line = _pair_rule_pass(line, is_p, lambda c: not is_n(c), " {0} {1}")
    return "".join(f" {ch} " if is_s(ch) else ch for ch in line)


class _SacreBLEUTokenizer:
    """Line tokenizers from the sacrebleu spec, selected by name."""

    _TOKENIZE_FN = {
        "none": "_tokenize_base",
        "13a": "_tokenize_13a",
        "zh": "_tokenize_zh",
        "intl": "_tokenize_international",
        "char": "_tokenize_char",
    }

    def __init__(self, tokenize: str, lowercase: bool = False) -> None:
        self.tokenize_fn = getattr(self, self._TOKENIZE_FN[tokenize])
        self.lowercase = lowercase

    def __call__(self, line: str) -> Sequence[str]:
        tokenized = self.tokenize_fn(line)
        return (tokenized.lower() if self.lowercase else tokenized).split()

    @classmethod
    def tokenize(cls, line: str, tokenize: str, lowercase: bool = False) -> Sequence[str]:
        tokenized = getattr(cls, cls._TOKENIZE_FN[tokenize])(line)
        return (tokenized.lower() if lowercase else tokenized).split()

    @classmethod
    def _tokenize_regex(cls, line: str) -> str:
        for pattern, repl in _13A_RULES:
            line = pattern.sub(repl, line)
        return " ".join(line.split())

    @staticmethod
    def _is_chinese_char(uchar: str) -> bool:
        return any(start <= uchar <= end for start, end in _UCODE_RANGES)

    @classmethod
    def _tokenize_base(cls, line: str) -> str:
        return line

    @classmethod
    def _tokenize_13a(cls, line: str) -> str:
        line = line.replace("<skipped>", "").replace("-\n", "").replace("\n", " ")
        if "&" in line:
            line = line.replace("&quot;", '"').replace("&amp;", "&").replace("&lt;", "<").replace("&gt;", ">")
        return cls._tokenize_regex(line)

    @classmethod
    def _tokenize_zh(cls, line: str) -> str:
        chars = []
        for ch in line.strip():
            chars.append(f" {ch} " if cls._is_chinese_char(ch) else ch)
        return cls._tokenize_regex("".join(chars))

    @classmethod
    def _tokenize_international(cls, line: str) -> str:
        if _REGEX_AVAILABLE:
            for pattern, repl in _INT_RULES:
                line = pattern.sub(repl, line)
        else:
            line = _intl_tokenize_fallback(line)
        return " ".join(line.split())

    @classmethod
    def _tokenize_char(cls, line: str) -> str:
        return " ".join(line)


def sacre_bleu_score(
    preds: Sequence[str],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    tokenize: str = "13a",
    lowercase: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU with sacrebleu's canonical tokenization.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> sacre_bleu_score(preds, target)
        Array(0.7598..., dtype=float32)
    """
    if tokenize not in AVAILABLE_TOKENIZERS:
        raise ValueError(f"Argument `tokenize` expected to be one of {AVAILABLE_TOKENIZERS} but got {tokenize}.")
    if len(preds) != len(target):
        raise ValueError(f"Corpus has different size {len(preds)} != {len(target)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]
    tokenize_fn = partial(_SacreBLEUTokenizer.tokenize, tokenize=tokenize, lowercase=lowercase)
    numerator, denominator, preds_len, target_len = _bleu_score_update(preds, target_, n_gram, tokenize_fn)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
