"""BLEU score functional (reference: functional/text/bleu.py:26-204).

N-gram counting is host-side (string inputs); sufficient statistics are four device
arrays — clipped-match and total n-gram count vectors of length ``n_gram`` plus the
two corpus-length scalars — all psum-reducible, so the metric shards over hosts the
same way scalar metrics do. The final compute is branchless jnp (safe-log + where)
rather than the reference's data-dependent early return, so ``compute_from`` stays
jittable.
"""
from collections import Counter
from typing import Callable, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array


def _count_ngram(tokens: Sequence[str], n_gram: int) -> Counter:
    """Counter of all 1..n_gram-grams (tuple keys) in a token sequence."""
    ngram_counter: Counter = Counter()
    for n in range(1, n_gram + 1):
        for j in range(len(tokens) - n + 1):
            ngram_counter[tuple(tokens[j : j + n])] += 1
    return ngram_counter


def _tokenize_fn(sentence: str) -> Sequence[str]:
    return sentence.split()


def _bleu_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    n_gram: int = 4,
    tokenizer: Callable[[str], Sequence[str]] = _tokenize_fn,
) -> Tuple[Array, Array, Array, Array]:
    """Per-call sufficient statistics: (numerator, denominator, preds_len, target_len).

    ``numerator[k]`` = reference-clipped (k+1)-gram matches; ``denominator[k]`` =
    total candidate (k+1)-grams; ``target_len`` uses the closest-length reference
    (ties resolved to the first, matching the canonical BLEU definition).
    """
    target_tok = [[tokenizer(line) if line else [] for line in t] for t in target]
    preds_tok = [tokenizer(line) if line else [] for line in preds]

    numerator = [0] * n_gram
    denominator = [0] * n_gram
    preds_len = 0
    target_len = 0
    for pred, targets in zip(preds_tok, target_tok):
        preds_len += len(pred)
        len_diffs = [abs(len(pred) - len(tgt)) for tgt in targets]
        target_len += len(targets[len_diffs.index(min(len_diffs))])

        preds_counter = _count_ngram(pred, n_gram)
        target_counter: Counter = Counter()
        for tgt in targets:
            target_counter |= _count_ngram(tgt, n_gram)
        clipped = preds_counter & target_counter

        for key, cnt in clipped.items():
            numerator[len(key) - 1] += cnt
        for key, cnt in preds_counter.items():
            denominator[len(key) - 1] += cnt

    return (
        jnp.asarray(numerator, jnp.float32),
        jnp.asarray(denominator, jnp.float32),
        jnp.asarray(preds_len, jnp.float32),
        jnp.asarray(target_len, jnp.float32),
    )


def _bleu_score_compute(
    preds_len: Array,
    target_len: Array,
    numerator: Array,
    denominator: Array,
    n_gram: int,
    weights: Sequence[float],
    smooth: bool,
) -> Array:
    if smooth:
        precision = (numerator + 1.0) / (denominator + 1.0)
        precision = precision.at[0].set(numerator[0] / denominator[0])
    else:
        precision = numerator / denominator
    # branchless: if any clipped-match count is zero the score is exactly 0
    any_zero = jnp.min(numerator) == 0.0
    safe_precision = jnp.where(precision > 0, precision, 1.0)
    log_precision = jnp.asarray(weights, jnp.float32) * jnp.log(safe_precision)
    geometric_mean = jnp.exp(jnp.sum(log_precision))
    brevity_penalty = jnp.where(preds_len > target_len, 1.0, jnp.exp(1 - target_len / preds_len))
    return jnp.where(any_zero, 0.0, brevity_penalty * geometric_mean)


def bleu_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_gram: int = 4,
    smooth: bool = False,
    weights: Optional[Sequence[float]] = None,
) -> Array:
    """BLEU score of machine-translated text against one or more references.

    Args:
        preds: machine-translated corpus.
        target: per-sample iterable of reference translations.
        n_gram: largest n-gram order (1-4 typical).
        smooth: apply add-one (Lin & Och) smoothing to orders > 1.
        weights: per-order weights (default uniform ``1/n_gram``).

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> bleu_score(preds, target)
        Array(0.7598..., dtype=float32)
    """
    preds_ = [preds] if isinstance(preds, str) else preds
    target_ = [[tgt] if isinstance(tgt, str) else tgt for tgt in target]

    if len(preds_) != len(target_):
        raise ValueError(f"Corpus has different size {len(preds_)} != {len(target_)}")
    if weights is not None and len(weights) != n_gram:
        raise ValueError(f"List of weights has different weights than `n_gram`: {len(weights)} != {n_gram}")
    if weights is None:
        weights = [1.0 / n_gram] * n_gram

    numerator, denominator, preds_len, target_len = _bleu_score_update(preds_, target_, n_gram, _tokenize_fn)
    return _bleu_score_compute(preds_len, target_len, numerator, denominator, n_gram, weights, smooth)
