"""Character error rate functional (reference: functional/text/cer.py:23-84)."""
from typing import Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distance, _validate_text_inputs


def _cer_update(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Tuple[Array, Array]:
    preds_l, target_l = _validate_text_inputs(preds, target)
    errors = 0
    total = 0
    for pred, tgt in zip(preds_l, target_l):
        errors += _edit_distance(list(pred), list(tgt))
        total += len(tgt)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _cer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def char_error_rate(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Array:
    """Character error rate for speech/OCR systems (0 = perfect).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> char_error_rate(preds=preds, target=target)
        Array(0.34146342, dtype=float32)
    """
    errors, total = _cer_update(preds, target)
    return _cer_compute(errors, total)
