"""chrF / chrF++ functional (reference: functional/text/chrf.py:48-637).

TPU-first state redesign: the reference keeps six ``{order: scalar tensor}``
dictionaries; here the sufficient statistics are six dense vectors —
``(n_char_order,)`` and ``(n_word_order,)`` counts for preds/target/matching —
which psum-reduce across a mesh axis in one collective each. Host-side n-gram
counting, device-side f-score compute.

Behavioral quirk preserved from the reference (chrf.py:360-367): the
best-reference selection uses a strict ``>`` against an initial 0.0, so when every
reference scores 0 the target/matching statistics of that sample are NOT
accumulated.
"""
from collections import Counter
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _validate_text_inputs

_EPS_SMOOTHING = 1e-16
# punctuation set from the published chrF implementation
_PUNCTUATIONS = set("!\"#$%&'()*+,-./:;<=>?@[\\]^_`{|}~")


def _get_characters(sentence: str, whitespace: bool) -> List[str]:
    if whitespace:
        return list(sentence)
    return list(sentence.strip().replace(" ", ""))


def _separate_word_and_punctuation(word: str) -> List[str]:
    """Split a leading/trailing punctuation char off a word (chrF++ word stream)."""
    if len(word) == 1:
        return [word]
    if word[-1] in _PUNCTUATIONS:
        return [word[:-1], word[-1]]
    if word[0] in _PUNCTUATIONS:
        return [word[0], word[1:]]
    return [word]


def _get_words_and_punctuation(sentence: str) -> List[str]:
    out: List[str] = []
    for word in sentence.strip().split():
        out.extend(_separate_word_and_punctuation(word))
    return out


def _ngram_counts(tokens: List[str], n_gram_order: int) -> List[Counter]:
    """Per-order n-gram Counters, index k = (k+1)-grams."""
    return [
        Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))
        for n in range(1, n_gram_order + 1)
    ]


def _sentence_counts(
    sentence: str, n_char_order: int, n_word_order: int, lowercase: bool, whitespace: bool
) -> Tuple[List[Counter], List[Counter], np.ndarray, np.ndarray]:
    if lowercase:
        sentence = sentence.lower()
    char_counts = _ngram_counts(_get_characters(sentence, whitespace), n_char_order)
    word_counts = _ngram_counts(_get_words_and_punctuation(sentence), n_word_order)
    char_totals = np.array([sum(c.values()) for c in char_counts], dtype=np.float64)
    word_totals = np.array([sum(c.values()) for c in word_counts], dtype=np.float64)
    return char_counts, word_counts, char_totals, word_totals


def _matches(hyp_counts: List[Counter], ref_counts: List[Counter]) -> np.ndarray:
    return np.array([sum((h & r).values()) for h, r in zip(hyp_counts, ref_counts)], dtype=np.float64)


def _fscore_from_stats(
    matching_char: np.ndarray,
    matching_word: np.ndarray,
    hyp_char: np.ndarray,
    hyp_word: np.ndarray,
    ref_char: np.ndarray,
    ref_word: np.ndarray,
    n_order: float,
    beta: float,
) -> float:
    """Mean per-order F-beta over char and word n-gram orders (host NumPy path)."""

    def _per_order(matching: np.ndarray, hyp: np.ndarray, ref: np.ndarray) -> np.ndarray:
        precision = np.where(hyp > 0, matching / np.maximum(hyp, 1e-300), 0.0)
        recall = np.where(ref > 0, matching / np.maximum(ref, 1e-300), 0.0)
        denom = np.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
        return (1 + beta**2) * precision * recall / denom

    char_f = _per_order(matching_char, hyp_char, ref_char)
    word_f = _per_order(matching_word, hyp_word, ref_word)
    return float((char_f.sum() + word_f.sum()) / n_order)


def _chrf_score_update(
    preds: Union[str, Sequence[str]],
    target: Union[Sequence[str], Sequence[Sequence[str]]],
    n_char_order: int,
    n_word_order: int,
    beta: float,
    lowercase: bool,
    whitespace: bool,
    collect_sentence_scores: bool = False,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray, Optional[List[float]]]:
    """Accumulate the six count vectors over a batch; best reference per sample."""
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[t] if isinstance(t, str) else list(t) for t in target]
    _validate_text_inputs(list(preds), ["x"] * len(target_corpus))  # length check only

    n_order = float(n_char_order + n_word_order)
    total_preds_char = np.zeros(n_char_order)
    total_preds_word = np.zeros(n_word_order)
    total_target_char = np.zeros(n_char_order)
    total_target_word = np.zeros(n_word_order)
    total_matching_char = np.zeros(n_char_order)
    total_matching_word = np.zeros(n_word_order)
    sentence_scores: Optional[List[float]] = [] if collect_sentence_scores else None

    for pred, targets in zip(preds, target_corpus):
        p_char_counts, p_word_counts, p_char_tot, p_word_tot = _sentence_counts(
            pred, n_char_order, n_word_order, lowercase, whitespace
        )
        total_preds_char += p_char_tot
        total_preds_word += p_word_tot

        best_f = 0.0
        best_match_char = np.zeros(n_char_order)
        best_match_word = np.zeros(n_word_order)
        best_tgt_char = np.zeros(n_char_order)
        best_tgt_word = np.zeros(n_word_order)
        for tgt in targets:
            t_char_counts, t_word_counts, t_char_tot, t_word_tot = _sentence_counts(
                tgt, n_char_order, n_word_order, lowercase, whitespace
            )
            match_char = _matches(p_char_counts, t_char_counts)
            match_word = _matches(p_word_counts, t_word_counts)
            f = _fscore_from_stats(
                match_char, match_word, p_char_tot, p_word_tot, t_char_tot, t_word_tot, n_order, beta
            )
            if f > best_f:
                best_f = f
                best_match_char, best_match_word = match_char, match_word
                best_tgt_char, best_tgt_word = t_char_tot, t_word_tot

        if sentence_scores is not None:
            sentence_scores.append(best_f)
        total_target_char += best_tgt_char
        total_target_word += best_tgt_word
        total_matching_char += best_match_char
        total_matching_word += best_match_word

    return (
        total_preds_char,
        total_preds_word,
        total_target_char,
        total_target_word,
        total_matching_char,
        total_matching_word,
        sentence_scores,
    )


def _chrf_score_compute(
    total_preds_char: Array,
    total_preds_word: Array,
    total_target_char: Array,
    total_target_word: Array,
    total_matching_char: Array,
    total_matching_word: Array,
    n_order: float,
    beta: float,
) -> Array:
    """Corpus-level chrF from the six count vectors — branchless jnp."""

    def _per_order(matching: Array, hyp: Array, ref: Array) -> Array:
        precision = jnp.where(hyp > 0, matching / jnp.maximum(hyp, 1e-30), 0.0)
        recall = jnp.where(ref > 0, matching / jnp.maximum(ref, 1e-30), 0.0)
        denom = jnp.maximum(beta**2 * precision + recall, _EPS_SMOOTHING)
        return (1 + beta**2) * precision * recall / denom

    char_f = _per_order(total_matching_char, total_preds_char, total_target_char)
    word_f = _per_order(total_matching_word, total_preds_word, total_target_word)
    return ((jnp.sum(char_f) + jnp.sum(word_f)) / n_order).astype(jnp.float32)


def chrf_score(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    n_char_order: int = 6,
    n_word_order: int = 2,
    beta: float = 2.0,
    lowercase: bool = False,
    whitespace: bool = False,
    return_sentence_level_score: bool = False,
) -> Union[Array, Tuple[Array, Array]]:
    """chrF (``n_word_order=0``) / chrF++ (``n_word_order=2``, default) score.

    Example:
        >>> preds = ['the cat is on the mat']
        >>> target = [['there is a cat on the mat', 'a cat is on the mat']]
        >>> chrf_score(preds, target)
        Array(0.8640..., dtype=float32)
    """
    if not isinstance(n_char_order, int) or n_char_order < 1:
        raise ValueError("Expected argument `n_char_order` to be an integer greater than or equal to 1.")
    if not isinstance(n_word_order, int) or n_word_order < 0:
        raise ValueError("Expected argument `n_word_order` to be an integer greater than or equal to 0.")
    if beta < 0:
        raise ValueError("Expected argument `beta` to be greater than 0.")

    n_order = float(n_char_order + n_word_order)
    (pc, pw, tc, tw, mc, mw, sentence_scores) = _chrf_score_update(
        preds, target, n_char_order, n_word_order, beta, lowercase, whitespace, return_sentence_level_score
    )
    score = _chrf_score_compute(
        jnp.asarray(pc), jnp.asarray(pw), jnp.asarray(tc), jnp.asarray(tw), jnp.asarray(mc), jnp.asarray(mw),
        n_order, beta,
    )
    if return_sentence_level_score:
        return score, jnp.asarray(sentence_scores, jnp.float32)
    return score
