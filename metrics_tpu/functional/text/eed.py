"""Extended Edit Distance functional (reference: functional/text/eed.py:101-408).

Implements the published EED measure (Stanchev, Wang, Ney, WMT 2019): CDER-style
character alignment grid with an additional long-jump operation at reference
blanks, plus a coverage penalty for repeatedly-visited hypothesis positions.

The per-row update vectorizes the substitution/insertion candidates in NumPy with
the reference's exact float operations (``row[i-1] + sub`` / ``row[i] + ins``);
only the sequential deletion chain stays a scalar loop so that exact-tie argmin
selection (which feeds the coverage count) is bit-identical to the published
algorithm.
"""
import re
import unicodedata
from math import inf
from typing import List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _validate_text_inputs


def _eed_function(
    hyp: str,
    ref: str,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> float:
    """Sentence-level EED between two preprocessed strings (spec: EED.py)."""
    n = len(hyp)
    number_of_visits = [-1] * (n + 1)
    row = [1.0] * (n + 1)
    row[0] = 0.0

    hyp_arr = np.array(list(hyp))
    for w in range(1, len(ref) + 1):
        row_np = np.asarray(row)
        sub_cost = (hyp_arr != ref[w - 1]).astype(np.float64)
        # candidates that don't depend on next_row itself, reference float ops
        base = np.minimum(row_np[:-1] + sub_cost, row_np[1:] + insertion)
        next_row = [row[0] + 1.0]
        prev = next_row[0]
        for i in range(1, n + 1):
            prev = min(prev + deletion, base[i - 1])
            next_row.append(prev)

        min_index = next_row.index(min(next_row))
        number_of_visits[min_index] += 1
        if ref[w - 1] == " ":
            jump = alpha + next_row[min_index]
            next_row = [min(x, jump) for x in next_row]
        row = next_row

    coverage = rho * sum(x if x >= 0 else 1 for x in number_of_visits)
    return min(1, (row[-1] + coverage) / (float(len(ref)) + coverage))


def _preprocess_en(sentence: str) -> str:
    """English preprocessing per the published EED util.py rules."""
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    sentence = sentence.rstrip()
    for pattern, replacement in [(".", " ."), ("!", " !"), ("?", " ?"), (",", " ,")]:
        sentence = sentence.replace(pattern, replacement)
    for pattern, replacement in [
        (r"\s+", r" "),
        (r"(\d) ([.,]) (\d)", r"\1\2\3"),
        (r"(Dr|Jr|Prof|Rev|Gen|Mr|Mt|Mrs|Ms) .", r"\1."),
    ]:
        sentence = re.sub(pattern, replacement, sentence)
    for pattern, replacement in [("e . g .", "e.g."), ("i . e .", "i.e."), ("U . S .", "U.S.")]:
        sentence = sentence.replace(pattern, replacement)
    return " " + sentence + " "


def _preprocess_ja(sentence: str) -> str:
    if not isinstance(sentence, str):
        raise ValueError(f"Only strings allowed during preprocessing step, found {type(sentence)} instead")
    return unicodedata.normalize("NFKC", sentence.rstrip())


def _eed_update(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
    sentence_eed: Optional[List[float]] = None,
) -> List[float]:
    """Best (lowest) per-sentence EED over references, appended to ``sentence_eed``."""
    if isinstance(preds, str):
        preds = [preds]
    target_corpus = [[t] if isinstance(t, str) else list(t) for t in target]
    _validate_text_inputs(list(preds), ["x"] * len(target_corpus))  # length check only

    if language == "en":
        preprocess = _preprocess_en
    elif language == "ja":
        preprocess = _preprocess_ja
    else:
        raise ValueError(f"Expected argument `language` to either be `en` or `ja` but got {language}")

    preds_p = [preprocess(p) for p in preds]
    target_p = [[preprocess(t) for t in refs] for refs in target_corpus]

    if sentence_eed is None:
        sentence_eed = []
    if 0 in (len(preds_p), len(target_p[0]) if target_p else 0):
        return sentence_eed

    for hypothesis, references in zip(preds_p, target_p):
        best = inf
        for reference in references:
            score = _eed_function(hypothesis, reference, alpha, rho, deletion, insertion)
            if score < best:
                best = score
        sentence_eed.append(best)
    return sentence_eed


def _eed_compute(sentence_level_scores: List[float]) -> Array:
    if len(sentence_level_scores) == 0:
        return jnp.asarray(0.0, jnp.float32)
    return jnp.asarray(sum(sentence_level_scores) / len(sentence_level_scores), jnp.float32)


def extended_edit_distance(
    preds: Union[str, Sequence[str]],
    target: Sequence[Union[str, Sequence[str]]],
    language: str = "en",
    return_sentence_level_score: bool = False,
    alpha: float = 2.0,
    rho: float = 0.3,
    deletion: float = 0.2,
    insertion: float = 1.0,
) -> Union[Array, Tuple[Array, Array]]:
    """Extended edit distance (lower = better; capped at 1 per sentence).

    Args:
        preds: hypothesis corpus.
        target: reference corpus (one or more references per hypothesis).
        language: ``"en"`` or ``"ja"`` preprocessing.
        return_sentence_level_score: also return the per-sentence scores.
        alpha: long-jump penalty.
        rho: coverage (re-visit) penalty.
        deletion: deletion cost.
        insertion: insertion/substitution cost.

    Example:
        >>> preds = ["this is the prediction", "here is an other sample"]
        >>> target = ["this is the reference", "here is another one"]
        >>> extended_edit_distance(preds=preds, target=target)
        Array(0.3077..., dtype=float32)
    """
    for param_name, param in zip(["alpha", "rho", "deletion", "insertion"], [alpha, rho, deletion, insertion]):
        if not isinstance(param, float) or param < 0:
            raise ValueError(f"Parameter `{param_name}` is expected to be a non-negative float.")

    sentence_level_scores = _eed_update(preds, target, language, alpha, rho, deletion, insertion)
    average = _eed_compute(sentence_level_scores)
    if return_sentence_level_score:
        return average, jnp.asarray(sentence_level_scores, jnp.float32)
    return average
