"""Word error rate functional (reference: functional/text/wer.py:23-84)."""
from typing import List, Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distance, _validate_text_inputs


def _wer_update(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Tuple[Array, Array]:
    preds_l, target_l = _validate_text_inputs(preds, target)
    errors = 0
    total = 0
    for pred, tgt in zip(preds_l, target_l):
        pred_tokens: List[str] = pred.split()
        tgt_tokens: List[str] = tgt.split()
        errors += _edit_distance(pred_tokens, tgt_tokens)
        total += len(tgt_tokens)
    return jnp.asarray(errors, jnp.float32), jnp.asarray(total, jnp.float32)


def _wer_compute(errors: Array, total: Array) -> Array:
    return errors / total


def word_error_rate(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Array:
    """Word error rate for speech recognition (0 = perfect).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_error_rate(preds=preds, target=target)
        Array(0.5, dtype=float32)
    """
    errors, total = _wer_update(preds, target)
    return _wer_compute(errors, total)
