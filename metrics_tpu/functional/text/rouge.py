"""ROUGE-N / ROUGE-L / ROUGE-LSum functional (reference: functional/text/rouge.py:63-516).

Host-side string metric. The LCS dynamic program — the hot kernel for rougeL/LSum —
is vectorized per DP row in NumPy: the left-to-right propagation
``L[i][j] = max(cand[j], L[i][j-1])`` is a running maximum, so each row is
``np.maximum.accumulate(max(P[1:], P[:-1] + match))`` (valid because
``L[i-1][j-1] + 1 >= L[i][j-1]`` and ``L[i-1][j-1] <= L[i-1][j]`` make the relaxed
candidates harmless), replacing the reference's pure-Python double loop.

Sentence splitting for rougeLsum uses nltk punkt when its data is installed and a
regex splitter otherwise (offline-safe), matching the google-research scorer's
intent of per-sentence union-LCS.
"""
import re
from collections import Counter
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.text.helper import _token_ids
from metrics_tpu.utils.imports import _NLTK_AVAILABLE

ALLOWED_ROUGE_KEYS: Dict[str, Union[int, str]] = {
    "rouge1": 1,
    "rouge2": 2,
    "rouge3": 3,
    "rouge4": 4,
    "rouge5": 5,
    "rouge6": 6,
    "rouge7": 7,
    "rouge8": 8,
    "rouge9": 9,
    "rougeL": "L",
    "rougeLsum": "Lsum",
}
ALLOWED_ACCUMULATE_VALUES = ("avg", "best")

_SENTENCE_RE = re.compile(r"(?<=[.!?])\s+")


def _split_sentence(x: str) -> Sequence[str]:
    """Sentence-split for rougeLsum: nltk punkt if its data exists, else regex."""
    x = re.sub("<n>", "", x)  # pegasus newline token
    if _NLTK_AVAILABLE:
        try:
            import nltk

            nltk.data.find("tokenizers/punkt.zip")
            return nltk.sent_tokenize(x)
        except LookupError:
            pass
    return [s for s in _SENTENCE_RE.split(x) if s]


def _compute_metrics(hits_or_lcs: int, pred_len: int, target_len: int) -> Dict[str, float]:
    precision = hits_or_lcs / pred_len
    recall = hits_or_lcs / target_len
    if precision == recall == 0.0:
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    return {"precision": precision, "recall": recall, "fmeasure": 2 * precision * recall / (precision + recall)}


def _lcs_len(pred_tokens: Sequence[str], target_tokens: Sequence[str]) -> int:
    """LCS length via row-vectorized DP (see module docstring)."""
    vocab: Dict[str, int] = {}
    a, b = _token_ids(pred_tokens, vocab), _token_ids(target_tokens, vocab)
    if len(a) == 0 or len(b) == 0:
        return 0
    if len(a) > len(b):  # loop over the shorter sequence, vectorize the longer row
        a, b = b, a
    prev = np.zeros(len(b) + 1, dtype=np.int32)
    for i in range(1, len(a) + 1):
        match = (b == a[i - 1]).astype(np.int32)
        cand = np.maximum(prev[1:], prev[:-1] + match)
        row = np.empty_like(prev)
        row[0] = 0
        np.maximum.accumulate(cand, out=row[1:])
        prev = row
    return int(prev[-1])


def _lcs_table(pred_ids: np.ndarray, target_ids: np.ndarray) -> np.ndarray:
    """Full (target+1, pred+1) LCS table, row-vectorized."""
    table = np.zeros((len(target_ids) + 1, len(pred_ids) + 1), dtype=np.int32)
    for i in range(1, len(target_ids) + 1):
        match = (pred_ids == target_ids[i - 1]).astype(np.int32)
        cand = np.maximum(table[i - 1, 1:], table[i - 1, :-1] + match)
        np.maximum.accumulate(cand, out=table[i, 1:])
    return table


def _backtracked_lcs_indices(pred_ids: np.ndarray, target_ids: np.ndarray) -> List[int]:
    """Indices into ``target`` of one longest common subsequence."""
    table = _lcs_table(pred_ids, target_ids)
    i, j = len(pred_ids), len(target_ids)
    out: List[int] = []
    while i > 0 and j > 0:
        if pred_ids[i - 1] == target_ids[j - 1]:
            out.append(j - 1)
            i -= 1
            j -= 1
        elif table[j, i - 1] > table[j - 1, i]:
            i -= 1
        else:
            j -= 1
    out.reverse()
    return out


def _union_lcs(pred_tokens_list: Sequence[Sequence[str]], target_tokens: Sequence[str]) -> List[str]:
    """Union over pred sentences of LCS index sets against one target sentence."""
    vocab: Dict[str, int] = {}
    tgt_ids = _token_ids(target_tokens, vocab)
    union: set = set()
    for pred_tokens in pred_tokens_list:
        union.update(_backtracked_lcs_indices(_token_ids(pred_tokens, vocab), tgt_ids))
    return [target_tokens[i] for i in sorted(union)]


def _normalize_and_tokenize_text(
    text: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> List[str]:
    """Lowercase + strip non-alphanumerics (or user normalizer), split, optional Porter stem."""
    text = normalizer(text) if callable(normalizer) else re.sub(r"[^a-z0-9]+", " ", text.lower())
    tokens = tokenizer(text) if callable(tokenizer) else re.split(r"\s+", text)
    if stemmer:
        tokens = [stemmer.stem(x) if len(x) > 3 else x for x in tokens]
    return [x for x in tokens if isinstance(x, str) and len(x) > 0]


def _rouge_n_score(pred: Sequence[str], target: Sequence[str], n_gram: int) -> Dict[str, float]:
    def _ngrams(tokens: Sequence[str], n: int) -> Counter:
        return Counter(tuple(tokens[i : i + n]) for i in range(len(tokens) - n + 1))

    pred_ngrams, target_ngrams = _ngrams(pred, n_gram), _ngrams(target, n_gram)
    pred_len, target_len = sum(pred_ngrams.values()), sum(target_ngrams.values())
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    hits = sum((pred_ngrams & target_ngrams).values())
    return _compute_metrics(hits, max(pred_len, 1), max(target_len, 1))


def _rouge_l_score(pred: Sequence[str], target: Sequence[str]) -> Dict[str, float]:
    if 0 in (len(pred), len(target)):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}
    return _compute_metrics(_lcs_len(pred, target), len(pred), len(target))


def _rouge_lsum_score(pred: Sequence[Sequence[str]], target: Sequence[Sequence[str]]) -> Dict[str, float]:
    """Per-sentence union-LCS hits with clipped token counts (google-research scorer)."""
    pred_len = sum(map(len, pred))
    target_len = sum(map(len, target))
    if 0 in (pred_len, target_len):
        return {"precision": 0.0, "recall": 0.0, "fmeasure": 0.0}

    pred_counts: Counter = Counter()
    target_counts: Counter = Counter()
    for sentence in pred:
        pred_counts.update(sentence)
    for sentence in target:
        target_counts.update(sentence)

    hits = 0
    for tgt in target:
        for token in _union_lcs(pred, tgt):
            if pred_counts[token] > 0 and target_counts[token] > 0:
                hits += 1
                pred_counts[token] -= 1
                target_counts[token] -= 1
    return _compute_metrics(hits, pred_len, target_len)


def _rouge_score_update(
    preds: Sequence[str],
    target: Sequence[Sequence[str]],
    rouge_keys_values: List[Union[int, str]],
    accumulate: str,
    stemmer: Optional[Any] = None,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
) -> Dict[Union[int, str], List[Dict[str, float]]]:
    """Per-sample scores per rouge key; multi-reference resolved via ``accumulate``.

    ``best`` keeps the reference with the highest fmeasure on the FIRST rouge key
    (reference behavior, rouge.py:364-370); ``avg`` averages each stat over refs.
    """
    results: Dict[Union[int, str], List[Dict[str, float]]] = {key: [] for key in rouge_keys_values}

    for pred_raw, target_raw in zip(preds, target):
        pred = _normalize_and_tokenize_text(pred_raw, stemmer, normalizer, tokenizer)
        if "Lsum" in rouge_keys_values:
            pred_lsum = [
                _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer) for s in _split_sentence(pred_raw)
            ]

        per_ref: List[Dict[Union[int, str], Dict[str, float]]] = []
        for target_raw_inner in target_raw:
            tgt = _normalize_and_tokenize_text(target_raw_inner, stemmer, normalizer, tokenizer)
            if "Lsum" in rouge_keys_values:
                target_lsum = [
                    _normalize_and_tokenize_text(s, stemmer, normalizer, tokenizer)
                    for s in _split_sentence(target_raw_inner)
                ]
            scores: Dict[Union[int, str], Dict[str, float]] = {}
            for key in rouge_keys_values:
                if isinstance(key, int):
                    scores[key] = _rouge_n_score(pred, tgt, key)
                elif key == "L":
                    scores[key] = _rouge_l_score(pred, tgt)
                else:  # Lsum
                    scores[key] = _rouge_lsum_score(pred_lsum, target_lsum)
            per_ref.append(scores)

        if accumulate == "best":
            first_key = rouge_keys_values[0]
            best_idx = int(np.argmax([ref[first_key]["fmeasure"] for ref in per_ref]))
            for key in rouge_keys_values:
                results[key].append(per_ref[best_idx][key])
        else:  # avg
            for key in rouge_keys_values:
                stats = per_ref[0][key].keys()
                results[key].append(
                    {stat: float(np.mean([ref[key][stat] for ref in per_ref])) for stat in stats}
                )

    return results


def _rouge_score_compute(sentence_results: Dict[str, List[float]]) -> Dict[str, Array]:
    return {key: jnp.asarray(np.mean(scores), jnp.float32) for key, scores in sentence_results.items()}


def rouge_score(
    preds: Union[str, Sequence[str]],
    target: Union[str, Sequence[str], Sequence[Sequence[str]]],
    accumulate: str = "best",
    use_stemmer: bool = False,
    normalizer: Optional[Callable[[str], str]] = None,
    tokenizer: Optional[Callable[[str], Sequence[str]]] = None,
    rouge_keys: Union[str, Tuple[str, ...]] = ("rouge1", "rouge2", "rougeL", "rougeLsum"),
) -> Dict[str, Array]:
    """ROUGE scores for automatic summarization.

    Args:
        preds: predicted sentence(s).
        target: reference sentence(s), optionally several per prediction.
        accumulate: multi-reference handling — ``"best"`` (highest fmeasure) or ``"avg"``.
        use_stemmer: Porter-stem tokens longer than 3 chars (requires nltk).
        normalizer: custom text normalizer (default: lowercase, alnum-only).
        tokenizer: custom tokenizer (default: whitespace split).
        rouge_keys: any of ``rouge1``..``rouge9``, ``rougeL``, ``rougeLsum``.

    Example:
        >>> preds = "My name is John"
        >>> target = "Is your name John"
        >>> rouge_score(preds, target, rouge_keys=("rouge1", "rougeL"))  # doctest: +NORMALIZE_WHITESPACE
        {'rouge1_fmeasure': Array(0.75, dtype=float32),
         'rouge1_precision': Array(0.75, dtype=float32),
         'rouge1_recall': Array(0.75, dtype=float32),
         'rougeL_fmeasure': Array(0.5, dtype=float32),
         'rougeL_precision': Array(0.5, dtype=float32),
         'rougeL_recall': Array(0.5, dtype=float32)}
    """
    if accumulate not in ALLOWED_ACCUMULATE_VALUES:
        raise ValueError(
            f"Got unknown accumulate value {accumulate}. Expected to be one of {ALLOWED_ACCUMULATE_VALUES}"
        )
    if use_stemmer and not _NLTK_AVAILABLE:
        raise ModuleNotFoundError("Stemmer requires that `nltk` is installed. Use `pip install nltk`.")
    stemmer = None
    if use_stemmer:
        import nltk

        stemmer = nltk.stem.porter.PorterStemmer()

    if not isinstance(rouge_keys, tuple):
        rouge_keys = (rouge_keys,)
    for key in rouge_keys:
        if key not in ALLOWED_ROUGE_KEYS:
            raise ValueError(f"Got unknown rouge key {key}. Expected to be one of {list(ALLOWED_ROUGE_KEYS.keys())}")
    rouge_keys_values = [ALLOWED_ROUGE_KEYS[key] for key in rouge_keys]

    if isinstance(target, list) and all(isinstance(tgt, str) for tgt in target):
        target = [target] if isinstance(preds, str) else [[tgt] for tgt in target]
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [[target]]

    sentence_results = _rouge_score_update(
        preds, target, rouge_keys_values, accumulate, stemmer, normalizer, tokenizer
    )

    output: Dict[str, List[float]] = {}
    for key, metrics in sentence_results.items():
        for stat in ["fmeasure", "precision", "recall"]:
            output[f"rouge{key}_{stat}"] = [m[stat] for m in metrics]
    return _rouge_score_compute(output)
