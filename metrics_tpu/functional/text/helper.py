"""Shared text-metric machinery: tokenization + edit-distance kernels.

Capability parity with reference ``functional/text/helper.py`` (``_edit_distance``
at helper.py:324, ``_validate_inputs`` at helper.py:406). The reference computes
Levenshtein distance with a pure-Python O(N·M) double loop; here the row recurrence
is vectorized over the inner dimension with a prefix-min trick so each DP row is a
handful of NumPy array ops (the sequential ``insertion`` dependency
``row[j] = min(cand[j], row[j-1]+1)`` is equivalent to
``row[j] = j + cummin(cand[k]-k)``), ~50x faster on long transcripts. String
metrics are host-side by design — inputs are Python strings, not arrays; only the
accumulated sufficient statistics live on device (SURVEY.md §2.9).
"""
from typing import Dict, List, Sequence, Tuple, Union

import numpy as np


def _validate_text_inputs(
    preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
) -> Tuple[List[str], List[str]]:
    """Normalize ``str | Sequence[str]`` inputs to equal-length lists."""
    if isinstance(preds, str):
        preds = [preds]
    if isinstance(target, str):
        target = [target]
    preds, target = list(preds), list(target)
    if len(preds) != len(target):
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same length, got {len(preds)} and {len(target)}"
        )
    return preds, target


def _token_ids(tokens: Sequence, vocab: Dict) -> np.ndarray:
    """Map hashable tokens to dense int32 ids (shared ``vocab`` grows in place)."""
    return np.fromiter(
        (vocab.setdefault(tok, len(vocab)) for tok in tokens), dtype=np.int32, count=len(tokens)
    )


def _levenshtein_ids(a: np.ndarray, b: np.ndarray) -> int:
    """Levenshtein distance between two int id sequences, vectorized per DP row.

    Row recurrence: with previous row ``P`` and substitution costs ``c[j]``,
    ``cand[j] = min(P[j] + 1, P[j-1] + c[j])`` is elementwise; the remaining
    left-to-right insertion term is folded in as
    ``row[j] = j + cummin_k<=j (m[k] - k)`` where ``m[0] = i`` and ``m[k] = cand[k]``.
    """
    n, m = len(a), len(b)
    if n == 0:
        return m
    if m == 0:
        return n
    if n > m:  # loop over the shorter sequence, vectorize the longer row
        a, b, n, m = b, a, m, n
    offsets = np.arange(m + 1, dtype=np.int64)
    prev = offsets.copy()
    t = np.empty(m + 1, dtype=np.int64)
    for i in range(1, n + 1):
        cost = (b != a[i - 1]).astype(np.int64)
        cand = np.minimum(prev[1:] + 1, prev[:-1] + cost)
        t[0] = i
        np.subtract(cand, offsets[1:], out=t[1:])
        np.minimum.accumulate(t, out=t)
        prev = t + offsets
        t = np.empty(m + 1, dtype=np.int64)
    return int(prev[m])


def _edit_distance(prediction_tokens: Sequence, reference_tokens: Sequence) -> int:
    """Edit distance between two token sequences (reference: helper.py:324)."""
    vocab: Dict = {}
    return _levenshtein_ids(_token_ids(prediction_tokens, vocab), _token_ids(reference_tokens, vocab))


def _tokens_idf(input_ids: np.ndarray) -> Dict:
    """Inverse document frequencies over a tokenized corpus: log((N+1)/(df+1)).

    Shared by BERTScore and InfoLM (both weight token positions by target-corpus
    IDF). The ``"__default__"`` entry is the out-of-corpus value log(N+1).
    """
    import math
    from collections import Counter

    num_sentences = input_ids.shape[0]
    counter: Counter = Counter()
    for row in input_ids:
        counter.update(set(row.tolist()))
    idf: Dict = {idx: math.log((num_sentences + 1) / (occurrence + 1)) for idx, occurrence in counter.items()}
    idf["__default__"] = math.log(num_sentences + 1)
    return idf


def _input_ids_idf(input_ids: np.ndarray, idf_map: Dict) -> np.ndarray:
    """Per-position IDF weights for a tokenized batch (unknown ids -> default)."""
    default = idf_map["__default__"]
    return np.vectorize(lambda t: idf_map.get(int(t), default))(input_ids).astype(np.float32)
