"""Root-functional deprecation shims (reference: functional/text/_deprecated.py).

``metrics_tpu.functional.<name>`` warns; ``metrics_tpu.functional.text.<name>``
stays silent (reference utilities/prints.py:67-72).
"""
from metrics_tpu.functional.text import bleu_score, char_error_rate, chrf_score, extended_edit_distance, match_error_rate, perplexity, rouge_score, sacre_bleu_score, squad, translation_edit_rate, word_error_rate, word_information_lost, word_information_preserved, bert_score, infolm
from metrics_tpu.utils.prints import _root_func_shim

_bleu_score = _root_func_shim(bleu_score, "bleu_score", "text")
_char_error_rate = _root_func_shim(char_error_rate, "char_error_rate", "text")
_chrf_score = _root_func_shim(chrf_score, "chrf_score", "text")
_extended_edit_distance = _root_func_shim(extended_edit_distance, "extended_edit_distance", "text")
_match_error_rate = _root_func_shim(match_error_rate, "match_error_rate", "text")
_perplexity = _root_func_shim(perplexity, "perplexity", "text")
_rouge_score = _root_func_shim(rouge_score, "rouge_score", "text")
_sacre_bleu_score = _root_func_shim(sacre_bleu_score, "sacre_bleu_score", "text")
_squad = _root_func_shim(squad, "squad", "text")
_translation_edit_rate = _root_func_shim(translation_edit_rate, "translation_edit_rate", "text")
_word_error_rate = _root_func_shim(word_error_rate, "word_error_rate", "text")
_word_information_lost = _root_func_shim(word_information_lost, "word_information_lost", "text")
_word_information_preserved = _root_func_shim(word_information_preserved, "word_information_preserved", "text")
_bert_score = _root_func_shim(bert_score, "bert_score", "text")
_infolm = _root_func_shim(infolm, "infolm", "text")

__all__ = ["_bleu_score", "_char_error_rate", "_chrf_score", "_extended_edit_distance", "_match_error_rate", "_perplexity", "_rouge_score", "_sacre_bleu_score", "_squad", "_translation_edit_rate", "_word_error_rate", "_word_information_lost", "_word_information_preserved", "_bert_score", "_infolm"]
