"""Word information lost functional (reference: functional/text/wil.py:22-91).

The reference accumulates ``errors - total`` (a negative "minus hits" count) and
relies on sign cancellation in the product; here the state is the non-negative hit
count ``hits = sum(max(|ref|, |hyp|)) - edit_errors`` directly — numerically
identical, but meaningful on its own and psum-friendly.
"""
from typing import Sequence, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.text.helper import _edit_distance, _validate_text_inputs


def _wil_update(
    preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]
) -> Tuple[Array, Array, Array]:
    preds_l, target_l = _validate_text_inputs(preds, target)
    hits = 0
    target_total = 0
    preds_total = 0
    for pred, tgt in zip(preds_l, target_l):
        pred_tokens = pred.split()
        tgt_tokens = tgt.split()
        hits += max(len(tgt_tokens), len(pred_tokens)) - _edit_distance(pred_tokens, tgt_tokens)
        target_total += len(tgt_tokens)
        preds_total += len(pred_tokens)
    return (
        jnp.asarray(hits, jnp.float32),
        jnp.asarray(target_total, jnp.float32),
        jnp.asarray(preds_total, jnp.float32),
    )


def _wil_compute(hits: Array, target_total: Array, preds_total: Array) -> Array:
    return 1 - (hits / target_total) * (hits / preds_total)


def word_information_lost(preds: Union[str, Sequence[str]], target: Union[str, Sequence[str]]) -> Array:
    """Word information lost: ``1 - (hits/ref_len) * (hits/hyp_len)`` (0 = perfect).

    Example:
        >>> preds = ["this is the prediction", "there is an other sample"]
        >>> target = ["this is the reference", "there is another one"]
        >>> word_information_lost(preds=preds, target=target)
        Array(0.6527..., dtype=float32)
    """
    hits, target_total, preds_total = _wil_update(preds, target)
    return _wil_compute(hits, target_total, preds_total)
