"""Stat-scores (tp/fp/tn/fn) kernels — the shared core of the classification domain.

Capability parity with reference ``functional/classification/stat_scores.py`` (binary:
:25-225, multiclass: :228-600, multilabel: :600-780, dispatcher: :780-890), re-designed
for XLA/TPU:

- **Branchless formatting.** The reference branches on data (``if not torch.all(0<=p<=1):
  sigmoid``); here the sigmoid is applied via ``jnp.where`` on an ``all``-reduction so
  the whole format stage stays inside one jit trace with static shapes.
- **Masked ignore_index.** The reference drops ignored elements via boolean indexing
  (dynamic shapes); here ignored positions are masked out of every count — numerically
  identical, jit-safe.
- **Confusion-matrix counting tiers** (reference :404-410 uses one bincount): small C
  goes through the Pallas/compare histogram tiers, medium C through a one-hot MXU
  matmul (ops/confmat.py, 13-16x the scatter-add fallback on TPU); all deterministic.
- Validation (`*_tensor_validation`) runs on host values and is skippable with
  ``validate_args=False`` for fully-jitted pipelines, mirroring the reference contract.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.ops.confmat import confusion_counts
from metrics_tpu.ops.streaming import argmax_correct_count, eq_count
from metrics_tpu.utils.checks import _check_same_shape, _is_concrete
from metrics_tpu.utils.data import _count_dtype, select_topk
from metrics_tpu.utils.enums import ClassificationTask

Literal = str  # annotations only


def _is_floating(x: Array) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _sigmoid_if_logits(preds: Array) -> Array:
    """Apply sigmoid iff any value is outside [0, 1] — branchless (both paths traced)."""
    is_prob = jnp.all((preds >= 0) & (preds <= 1))
    return jnp.where(is_prob, preds, jax.nn.sigmoid(preds))


def _softmax_if_logits(preds: Array, axis: int = 1) -> Array:
    """Softmax iff any value is outside [0, 1] — the multiclass analogue.

    Branchless, so jit/shard_map-safe. Decision granularity is per call
    (eagerly) / per shard (under shard_map); results are identical under the
    supported contract that one update's preds are homogeneous (all
    probabilities or all logits).
    """
    is_prob = jnp.all((preds >= 0) & (preds <= 1))
    return jnp.where(is_prob, preds, jax.nn.softmax(preds, axis=axis))


# ----------------------------------------------------------------------- binary


def _binary_stat_scores_arg_validation(
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    """Host-side data checks (value checks auto-skip under jit tracing)."""
    _check_same_shape(preds, target)
    if multidim_average != "global" and preds.ndim < 2:
        raise ValueError("Expected input to be atleast 2D when multidim_average is set to `samplewise`")
    if not _is_concrete(preds, target):
        return
    unique_values = np.unique(np.asarray(target))
    if ignore_index is None:
        check = np.any((unique_values != 0) & (unique_values != 1))
    else:
        check = np.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index))
    if check:
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [0, 1, ignore_index]}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if np.any((unique_values != 0) & (unique_values != 1)):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since `preds` is a label tensor."
            )


def _binary_stat_scores_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    """Probability/logit -> {0,1} labels; ignored positions -> target=-1 (masked)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    preds = preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _binary_stat_scores_update(
    preds: Array,
    target: Array,
    multidim_average: str = "global",
) -> Tuple[Array, Array, Array, Array]:
    """tp/fp/tn/fn counts; -1 targets fall out of every predicate."""
    sum_dim = (0, 1) if multidim_average == "global" else 1
    tp = jnp.squeeze(((target == preds) & (target == 1)).sum(sum_dim)).astype(jnp.int32)
    fn = jnp.squeeze(((target != preds) & (target == 1)).sum(sum_dim)).astype(jnp.int32)
    fp = jnp.squeeze(((target != preds) & (target == 0)).sum(sum_dim)).astype(jnp.int32)
    tn = jnp.squeeze(((target == preds) & (target == 0)).sum(sum_dim)).astype(jnp.int32)
    return tp, fp, tn, fn


def _binary_stat_scores_compute(
    tp: Array, fp: Array, tn: Array, fn: Array, multidim_average: str = "global"
) -> Array:
    return jnp.squeeze(
        jnp.stack([tp, fp, tn, fn, tp + fn], axis=0 if multidim_average == "global" else 1)
    )


def binary_stat_scores(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for binary tasks ``(..., 5)``.

    Reference: functional/classification/stat_scores.py:140-225.
    """
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, multidim_average)
    return _binary_stat_scores_compute(tp, fp, tn, fn, multidim_average)


# -------------------------------------------------------------------- multiclass


def _multiclass_stat_scores_arg_validation(
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if not isinstance(top_k, int) or top_k < 1:
        raise ValueError(f"Expected argument `top_k` to be an integer larger than or equal to 1, but got {top_k}")
    if top_k > num_classes:
        raise ValueError(
            f"Expected argument `top_k` to be smaller or equal to `num_classes` but got {top_k} and {num_classes}"
        )
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multiclass_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_classes: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if preds.ndim == target.ndim + 1:
        if not _is_floating(preds):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError(
                "If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                " equal to number of classes."
            )
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        if multidim_average != "global" and preds.ndim < 3:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should "
                " atleast 3D when multidim_average is set to `samplewise`"
            )
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if multidim_average != "global" and preds.ndim < 2:
            raise ValueError(
                "When `preds` and `target` have the same shape, the shape of `preds` should "
                " atleast 2D when multidim_average is set to `samplewise`"
            )
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )

    if not _is_concrete(preds, target):
        return
    num_unique_values = len(np.unique(np.asarray(target)))
    check = num_unique_values > num_classes if ignore_index is None else num_unique_values > num_classes + 1
    if check:
        raise RuntimeError(
            "Detected more unique values in `target` than `num_classes`. Expected only"
            f" {num_classes if ignore_index is None else num_classes + 1} but found"
            f" {num_unique_values} in `target`."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if len(unique_values) > num_classes:
            raise RuntimeError(
                "Detected more unique values in `preds` than `num_classes`. Expected only"
                f" {num_classes} but found {len(unique_values)} in `preds`."
            )


def _multiclass_stat_scores_format(
    preds: Array,
    target: Array,
    top_k: int = 1,
) -> Tuple[Array, Array]:
    """Argmax probabilities to labels (when top_k==1); flatten extra dims."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and top_k == 1:
        preds = preds.argmax(axis=1)
    preds = preds.reshape(*preds.shape[:2], -1) if top_k != 1 else preds.reshape(preds.shape[0], -1)
    target = target.reshape(target.shape[0], -1)
    return preds, target


def _micro_counts_from_tp(
    tp: Array, n_valid: Array, num_classes: int, exact_n: Optional[int] = None
) -> Tuple[Array, Array, Array, Array]:
    """Derive fp/fn/tn arithmetically from the fused tp count (micro average).

    ``exact_n`` (the static element count, when no ignore_index mask applies)
    keeps fp exact above 2^24 where the float32 count dtype loses integers;
    tn = C*n - ... can exceed int32 for a single huge update, so it is widened.
    """
    cd = _count_dtype()
    fp = (jnp.int32(exact_n) if exact_n is not None else n_valid.astype(jnp.int32)) - tp
    fn = fp
    tn = (num_classes * n_valid - (fp + fn + tp).astype(cd)).astype(cd)
    return tp, fp, tn, fn


def _multiclass_stat_scores_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Three regimes, all static-shape (reference :337-411):

    - samplewise / top_k>1: one-hot comparison sums,
    - micro: flat masked eq-sums,
    - macro/weighted/none: confusion-matrix via one weighted bincount (masked, no
      dynamic boolean indexing).
    """
    if multidim_average == "samplewise" or top_k != 1:
        ignore_in = 0 <= ignore_index <= num_classes - 1 if ignore_index is not None else None
        aug = ignore_index is not None and not ignore_in
        if aug:
            # out-of-range ignore_index: remap ignored positions to extra class C
            ignored = target == ignore_index
            target = jnp.where(ignored, num_classes, target)
            if preds.ndim == target.ndim:  # label preds (top_k == 1 path)
                preds = jnp.where(ignored, num_classes, preds)

        n_extra = 1 if aug else 0
        if top_k > 1:
            preds_oh = jnp.moveaxis(select_topk(preds, topk=top_k, dim=1), 1, -1)
        else:
            preds_oh = jax.nn.one_hot(preds, num_classes + n_extra, dtype=jnp.int32)
        target_oh = jax.nn.one_hot(target, num_classes + n_extra, dtype=jnp.int32)

        if ignore_index is not None:
            if ignore_in:
                mask = (target == ignore_index)[..., None]
            else:
                if top_k == 1:
                    preds_oh = preds_oh[..., :-1]
                target_oh = target_oh[..., :-1]
                mask = (target == num_classes)[..., None]
            target_oh = jnp.where(mask, -1, target_oh)

        sum_dim = (0, 1) if multidim_average == "global" else (1,)
        tp = ((target_oh == preds_oh) & (target_oh == 1)).sum(sum_dim).astype(jnp.int32)
        fn = ((target_oh != preds_oh) & (target_oh == 1)).sum(sum_dim).astype(jnp.int32)
        fp = ((target_oh != preds_oh) & (target_oh == 0)).sum(sum_dim).astype(jnp.int32)
        tn = ((target_oh == preds_oh) & (target_oh == 0)).sum(sum_dim).astype(jnp.int32)
        return tp, fp, tn, fn

    preds = preds.ravel()
    target = target.ravel()

    if average == "micro":
        if ignore_index is None:
            # hot streaming path: ONE fused compare-reduce (ops/streaming.py);
            # fp/n_valid derived arithmetically instead of two more reductions
            tp = eq_count(preds, target)
            n_valid = jnp.asarray(target.size, _count_dtype())
            return _micro_counts_from_tp(tp, n_valid, num_classes, exact_n=target.size)
        valid = target != ignore_index
        tp = ((preds == target) & valid).sum().astype(jnp.int32)
        n_valid = valid.sum().astype(_count_dtype())
        return _micro_counts_from_tp(tp, n_valid, num_classes)

    # confusion counts: weighted bincount or the one-hot MXU matmul tier
    # (ops/confmat.py) by class count/platform. NOTE: out-of-range labels are
    # clipped into [0, C-1] rather than erroring — XLA cannot raise on data
    # values; enable validate_args to catch bad labels.
    valid = jnp.ones_like(target, dtype=bool) if ignore_index is None else target != ignore_index
    confmat = confusion_counts(preds, target, valid, num_classes)
    tp = jnp.diag(confmat)
    fp = confmat.sum(0) - tp
    fn = confmat.sum(1) - tp
    tn = confmat.sum() - (fp + fn + tp)
    return tp, fp, tn, fn


def _multiclass_stat_scores_format_update(
    preds: Array,
    target: Array,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Format + update in one call so the hot micro path can fuse across the stage
    boundary: for float ``(N, C, ...)`` preds with ``average='micro'``/``top_k=1``/
    global reduction, argmax+eq+sum run in one dispatch with no int-label
    round-trip through the generic format contract
    (ops/streaming.py:argmax_correct_count has the measured lowering grid).
    All other paths are byte-identical to format -> update.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    fused = (
        preds.ndim == target.ndim + 1
        and top_k == 1
        and average == "micro"
        and multidim_average == "global"
        and _is_floating(preds)
    )
    if fused:
        probs = jnp.moveaxis(preds, 1, -1).reshape(-1, num_classes)
        flat_t = target.ravel()
        if ignore_index is None:
            tp = argmax_correct_count(probs, flat_t)
            n_valid = jnp.asarray(flat_t.size, _count_dtype())
            return _micro_counts_from_tp(tp, n_valid, num_classes, exact_n=flat_t.size)
        valid = flat_t != ignore_index
        tp = argmax_correct_count(probs, flat_t, valid)
        n_valid = valid.sum().astype(_count_dtype())
        return _micro_counts_from_tp(tp, n_valid, num_classes)
    preds, target = _multiclass_stat_scores_format(preds, target, top_k)
    return _multiclass_stat_scores_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )


def _multiclass_stat_scores_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_dim = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(sum_dim) if res.ndim > 1 else res
    if average == "macro":
        return res.astype(jnp.float32).mean(sum_dim)
    if average == "weighted":
        weight = (tp + fn).astype(jnp.float32)
        if multidim_average == "global":
            return (res * (weight / weight.sum()).reshape(*weight.shape, 1)).sum(sum_dim)
        return (res * (weight / weight.sum(-1, keepdims=True)).reshape(*weight.shape, 1)).sum(sum_dim)
    if average is None or average == "none":
        return res
    return None


def multiclass_stat_scores(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multiclass tasks.

    Reference: functional/classification/stat_scores.py:448-600.
    """
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_format_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )
    return _multiclass_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# -------------------------------------------------------------------- multilabel


def _multilabel_stat_scores_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )
    if multidim_average not in ("global", "samplewise"):
        raise ValueError(
            f"Expected argument `multidim_average` to be one of ('global', 'samplewise'), but got {multidim_average}"
        )
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _multilabel_stat_scores_tensor_validation(
    preds: Array,
    target: Array,
    num_labels: int,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    if multidim_average != "global" and preds.ndim < 3:
        raise ValueError("Expected input to be atleast 3D when multidim_average is set to `samplewise`")
    if not _is_concrete(preds, target):
        return
    unique_values = np.unique(np.asarray(target))
    if ignore_index is None:
        check = np.any((unique_values != 0) & (unique_values != 1))
    else:
        check = np.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index))
    if check:
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [0, 1, ignore_index]}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if np.any((unique_values != 0) & (unique_values != 1)):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )


def _multilabel_stat_scores_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        preds = (preds > threshold).astype(jnp.int32)
    preds = preds.reshape(*preds.shape[:2], -1)
    target = target.reshape(*target.shape[:2], -1)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _multilabel_stat_scores_update(
    preds: Array, target: Array, multidim_average: str = "global"
) -> Tuple[Array, Array, Array, Array]:
    sum_dim = (0, -1) if multidim_average == "global" else (-1,)
    tp = jnp.squeeze(((target == preds) & (target == 1)).sum(sum_dim)).astype(jnp.int32)
    fn = jnp.squeeze(((target != preds) & (target == 1)).sum(sum_dim)).astype(jnp.int32)
    fp = jnp.squeeze(((target != preds) & (target == 0)).sum(sum_dim)).astype(jnp.int32)
    tn = jnp.squeeze(((target == preds) & (target == 0)).sum(sum_dim)).astype(jnp.int32)
    return tp, fp, tn, fn


def _multilabel_stat_scores_compute(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
) -> Array:
    res = jnp.stack([tp, fp, tn, fn, tp + fn], axis=-1)
    sum_dim = 0 if multidim_average == "global" else 1
    if average == "micro":
        return res.sum(sum_dim)
    if average == "macro":
        return res.astype(jnp.float32).mean(sum_dim)
    if average == "weighted":
        w = (tp + fn).astype(jnp.float32)
        return (res * (w / w.sum()).reshape(*w.shape, 1)).sum(sum_dim)
    if average is None or average == "none":
        return res
    return None


def multilabel_stat_scores(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """tp/fp/tn/fn/support for multilabel tasks.

    Reference: functional/classification/stat_scores.py:697-780.
    """
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, multidim_average)
    return _multilabel_stat_scores_compute(tp, fp, tn, fn, average, multidim_average)


# ------------------------------------------------------- shared pipelines
# (tensor-validate -> format -> update; used by every stat-score-derived metric so the
# hot path is written once — accuracy/precision/recall/fbeta/specificity/hamming only
# differ in their reduce formula)


def _binary_stat_scores_pipeline(
    preds: Array,
    target: Array,
    threshold: float,
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    return _binary_stat_scores_update(preds, target, multidim_average)


def _multiclass_stat_scores_pipeline(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str],
    top_k: int,
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    return _multiclass_stat_scores_format_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )


def _multilabel_stat_scores_pipeline(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float,
    multidim_average: str,
    ignore_index: Optional[int],
    validate_args: bool,
) -> Tuple[Array, Array, Array, Array]:
    if validate_args:
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    return _multilabel_stat_scores_update(preds, target, multidim_average)


# -------------------------------------------------------------------- dispatcher


def stat_scores(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: functional/classification/stat_scores.py:783-890)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_stat_scores(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_stat_scores(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_stat_scores(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
