"""AUROC functionals.

Capability parity with reference ``functional/classification/auroc.py`` (_reduce_auroc
:45-69, binary :72-188, multiclass :191-302, multilabel :305-420, dispatcher :423-457).
Trapezoidal integration of the shared ROC state.
"""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _is_confmat_state,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from metrics_tpu.ops.clf_curve import (
    binary_auroc_exact,
    mcclish_partial_auc,
    multiclass_auroc_exact,
    multilabel_auroc_exact,
)
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.compute import _auc_compute_without_check, _safe_divide
from metrics_tpu.utils.enums import ClassificationTask
from metrics_tpu.utils.prints import rank_zero_warn


def _reduce_scores(res: Array, average: Optional[str], weights: Optional[Array]) -> Array:
    """NaN-dropping macro/weighted reduction of per-class scores (reference: auroc.py:56-69).

    jit-safe: the NaN warning is advisory and only emitted eagerly (the reduction
    math itself is branchless ``where`` masking).
    """
    if average is None or average == "none":
        return res
    if _is_concrete(res) and bool(jnp.isnan(res).any()):
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.where(idx, res, 0.0).sum() / idx.sum()
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights.astype(jnp.float32), 0.0)
        weights = _safe_divide(weights, weights.sum())
        return jnp.where(idx, res * weights, 0.0).sum()
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _reduce_auroc(
    fpr: Union[Array, List[Array]],
    tpr: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reference: auroc.py:45-69 (NaN classes dropped from the average)."""
    if isinstance(fpr, (jnp.ndarray, np.ndarray)) and not isinstance(fpr, (list, tuple)):
        res = _auc_compute_without_check(fpr, tpr, 1.0, axis=1)
    else:
        res = jnp.stack([_auc_compute_without_check(x, y, 1.0) for x, y in zip(fpr, tpr)])
    return _reduce_scores(res, average, weights)


def _binary_auroc_arg_validation(
    max_fpr: Optional[float] = None,
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if max_fpr is not None and not (isinstance(max_fpr, float) and 0 < max_fpr <= 1):
        raise ValueError(f"Arguments `max_fpr` should be a float in range (0, 1], but got: {max_fpr}")


def _binary_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    max_fpr: Optional[float] = None,
    pos_label: int = 1,
    tolerance: float = 0.0,
    tolerance_bits: int = 12,
) -> Array:
    """Reference: auroc.py:82-106 (incl. McClish-corrected partial AUC).

    Exact mode (``thresholds=None``) runs fully on device — sort+cumsum with
    tie-run collapsing (ops/clf_curve.py) instead of the reference's host path.
    ``tolerance > 0`` opts into the certified sublinear sketch tier when the
    bracket width fits (ops/clf_curve.py `_sketch_dispatch`).
    """
    if not _is_confmat_state(state):
        return binary_auroc_exact(
            state[0], state[1], max_fpr=max_fpr, tolerance=tolerance, tolerance_bits=tolerance_bits
        )
    fpr, tpr, _ = _binary_roc_compute(state, thresholds, pos_label)
    if max_fpr is None or max_fpr == 1:
        return _auc_compute_without_check(fpr, tpr, 1.0)

    # pure-jnp clip+interpolate (shared with the exact device kernel): the old
    # np.searchsorted path concretized the traced confusion state under jit —
    # the first true positive tmlint's TM-HOSTSYNC surfaced in this hot path
    max_area = jnp.asarray(max_fpr, dtype=jnp.float32)
    return mcclish_partial_auc(jnp.asarray(fpr), jnp.asarray(tpr), max_area)


def binary_auroc(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
    tolerance: float = 0.0,
    tolerance_bits: int = 12,
) -> Array:
    """Binary AUROC (reference: auroc.py:109-188).

    ``tolerance > 0`` permits the sublinear sketch tier: when the certified
    bracket width at ``tolerance_bits`` fits, the bracket midpoint is served
    (no sort); otherwise the exact tier runs unchanged.
    """
    if validate_args:
        _binary_auroc_arg_validation(max_fpr, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_auroc_compute(state, thresholds, max_fpr, tolerance=tolerance, tolerance_bits=tolerance_bits)


def _multiclass_auroc_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None), but got {average}"
        )


def _multiclass_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    """Reference: auroc.py:191-203. Exact mode: vmapped one-vs-rest device kernel."""
    if thresholds is None:
        res, pos = multiclass_auroc_exact(state[0], state[1])
        return _reduce_scores(res, average, weights=pos)
    fpr, tpr, _ = _multiclass_roc_compute(state, num_classes, thresholds)
    return _reduce_auroc(fpr, tpr, average, weights=state[0][:, 1, :].sum(-1).astype(jnp.float32))


def multiclass_auroc(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass AUROC (reference: auroc.py:206-302)."""
    if validate_args:
        _multiclass_auroc_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_auroc_compute(state, num_classes, average, thresholds)


def _multilabel_auroc_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )


def _multilabel_auroc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    """Reference: auroc.py:305-330. Exact mode: vmapped per-label device kernel
    (negative targets are excluded by the kernel's validity mask, so the micro
    flatten needs no host-side ignore filtering)."""
    if average == "micro":
        if _is_confmat_state(state) and thresholds is not None:
            return _binary_auroc_compute(state.sum(1), thresholds, max_fpr=None)
        preds = jnp.asarray(state[0]).ravel()
        target = jnp.asarray(state[1]).ravel()
        return _binary_auroc_compute((preds, target), thresholds, max_fpr=None)

    if thresholds is None:
        res, pos = multilabel_auroc_exact(state[0], state[1])
        return _reduce_scores(res, average, weights=pos)
    fpr, tpr, _ = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    return _reduce_auroc(fpr, tpr, average, weights=state[0][:, 1, :].sum(-1).astype(jnp.float32))


def multilabel_auroc(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel AUROC (reference: auroc.py:333-420)."""
    if validate_args:
        _multilabel_auroc_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_auroc_compute(state, num_labels, average, thresholds, ignore_index)


def auroc(
    preds: Array,
    target: Array,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    max_fpr: Optional[float] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: auroc.py:423-457)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_auroc(preds, target, max_fpr, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        return multiclass_auroc(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_auroc(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
