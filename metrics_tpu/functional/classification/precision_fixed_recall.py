"""Precision-at-fixed-recall functionals (reference: functional/classification/precision_fixed_recall.py)."""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.recall_fixed_precision import (
    _binary_recall_at_fixed_precision_arg_validation,
    _binary_recall_at_fixed_precision_compute,
    _lexicographic_best,
    _multiclass_recall_at_fixed_precision_arg_compute,
    _multiclass_recall_at_fixed_precision_arg_validation,
    _multilabel_recall_at_fixed_precision_arg_compute,
    _multilabel_recall_at_fixed_precision_arg_validation,
)
from metrics_tpu.utils.enums import ClassificationTask


def _precision_at_recall(
    precision: Array,
    recall: Array,
    thresholds: Array,
    min_recall: float,
) -> Tuple[Array, Array]:
    """Reference: precision_fixed_recall.py:42-60 (max precision s.t. recall >= min)."""
    return _lexicographic_best(precision, recall, thresholds, min_recall)


def binary_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest precision given minimum recall, binary (reference: precision_fixed_recall.py:63-138).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_precision_at_fixed_recall
        >>> preds = jnp.array([0, 0.5, 0.7, 0.8])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> binary_precision_at_fixed_recall(preds, target, min_recall=0.5, thresholds=5)
        (Array(0.6666667, dtype=float32), Array(0.5, dtype=float32))
    """
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_recall, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_recall_at_fixed_precision_compute(
        state, thresholds, min_recall, reduce_fn=_precision_at_recall
    )


def multiclass_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_classes: int,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest precision given minimum recall, multiclass (reference: precision_fixed_recall.py:141-231)."""
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_recall, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_recall_at_fixed_precision_arg_compute(
        state, num_classes, thresholds, min_recall, reduce_fn=_precision_at_recall
    )


def multilabel_precision_at_fixed_recall(
    preds: Array,
    target: Array,
    num_labels: int,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest precision given minimum recall, multilabel (reference: precision_fixed_recall.py:234-313)."""
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_recall, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_recall_at_fixed_precision_arg_compute(
        state, num_labels, thresholds, ignore_index, min_recall, reduce_fn=_precision_at_recall
    )


def precision_at_fixed_recall(
    preds: Array,
    target: Array,
    task: str,
    min_recall: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array], Tuple[List[Array], List[Array]]]:
    """Dispatcher (reference: precision_fixed_recall.py:316-365)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_at_fixed_recall(preds, target, min_recall, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_at_fixed_recall(
            preds, target, num_classes, min_recall, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_at_fixed_recall(
            preds, target, num_labels, min_recall, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
