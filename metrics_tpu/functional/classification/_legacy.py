"""Legacy classification input pipeline, used by ``Dice`` (reference:
utilities/checks.py:206-452 ``_input_format_classification`` and
functional/classification/stat_scores.py:845-1060 ``_stat_scores_update`` /
``_reduce_stat_scores``).

Input-case detection is inherently data/shape-dependent Python dispatch, so it runs
host-side (NumPy checks); the produced one-hot stat-score reductions are jnp ops.
"""
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.data import select_topk, to_onehot
from metrics_tpu.utils.enums import AverageMethod, DataType, MDMCAverageMethod


def _is_floating(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def _input_squeeze(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Remove excess dimensions (reference: checks.py:300-309)."""
    preds, target = jnp.asarray(preds), jnp.asarray(target)
    if preds.shape[0] == 1:
        preds = preds.squeeze()[None, ...]
        target = target.squeeze()[None, ...]
    else:
        preds, target = preds.squeeze(), target.squeeze()
    return preds, target


def _basic_input_validation(
    preds: Array, target: Array, threshold: float, multiclass: Optional[bool], ignore_index: Optional[int]
) -> None:
    """Reference: checks.py:47-73."""
    if preds.size == 0 and target.size == 0:
        return
    if _is_floating(target):
        raise ValueError("The `target` has to be an integer tensor.")
    t_min = int(np.asarray(target).min())
    if (ignore_index is None and t_min < 0) or (ignore_index and ignore_index >= 0 and t_min < 0):
        raise ValueError("The `target` has to be a non-negative tensor.")
    preds_float = _is_floating(preds)
    if not preds_float and int(np.asarray(preds).min()) < 0:
        raise ValueError("If `preds` are integers, they have to be non-negative.")
    if not preds.shape[0] == target.shape[0]:
        raise ValueError("The `preds` and `target` should have the same first dimension.")
    if multiclass is False and int(np.asarray(target).max()) > 1:
        raise ValueError("If you set `multiclass=False`, then `target` should not exceed 1.")
    if multiclass is False and not preds_float and int(np.asarray(preds).max()) > 1:
        raise ValueError("If you set `multiclass=False` and `preds` are integers, then `preds` should not exceed 1.")


def _check_shape_and_type_consistency(preds: Array, target: Array) -> Tuple[DataType, int]:
    """Reference: checks.py:76-129."""
    preds_float = _is_floating(preds)

    if preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
        if preds_float and target.size > 0 and int(np.asarray(target).max()) > 1:
            raise ValueError(
                "If `preds` and `target` are of shape (N, ...) and `preds` are floats, `target` should be binary."
            )
        if preds.ndim == 1 and preds_float:
            case = DataType.BINARY
        elif preds.ndim == 1 and not preds_float:
            case = DataType.MULTICLASS
        elif preds.ndim > 1 and preds_float:
            case = DataType.MULTILABEL
        else:
            case = DataType.MULTIDIM_MULTICLASS
        implied_classes = int(np.prod(preds.shape[1:])) if preds.size > 0 else 0
    elif preds.ndim == target.ndim + 1:
        if not preds_float:
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
        implied_classes = preds.shape[1] if preds.size > 0 else 0
        case = DataType.MULTICLASS if preds.ndim == 2 else DataType.MULTIDIM_MULTICLASS
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    return case, implied_classes


def _check_classification_inputs(
    preds: Array,
    target: Array,
    threshold: float,
    num_classes: Optional[int],
    multiclass: Optional[bool],
    top_k: Optional[int],
    ignore_index: Optional[int] = None,
) -> DataType:
    """Reference: checks.py:206-297 (condensed: same checks, same errors)."""
    _basic_input_validation(preds, target, threshold, multiclass, ignore_index)
    case, implied_classes = _check_shape_and_type_consistency(preds, target)

    if preds.shape != target.shape:
        if multiclass is False and implied_classes != 2:
            raise ValueError(
                "You have set `multiclass=False`, but have more than 2 classes in your data,"
                " based on the C dimension of `preds`."
            )
        if target.size > 0 and int(np.asarray(target).max()) >= implied_classes:
            raise ValueError(
                "The highest label in `target` should be smaller than the size of the `C` dimension of `preds`."
            )

    if num_classes:
        if case == DataType.BINARY:
            if num_classes > 2:
                raise ValueError("Your data is binary, but `num_classes` is larger than 2.")
            if num_classes == 2 and not multiclass:
                raise ValueError(
                    "Your data is binary and `num_classes=2`, but `multiclass` is not True."
                    " Set it to True if you want to transform binary data to multi-class format."
                )
            if num_classes == 1 and multiclass:
                raise ValueError(
                    "You have binary data and have set `multiclass=True`, but `num_classes` is 1."
                    " Either set `multiclass=None`(default) or set `num_classes=2`"
                    " to transform binary data to multi-class format."
                )
        elif case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS):
            if num_classes == 1 and multiclass is not False:
                raise ValueError(
                    "You have set `num_classes=1`, but predictions are integers."
                    " If you want to convert (multi-dimensional) multi-class data with 2 classes"
                    " to binary/multi-label, set `multiclass=False`."
                )
            if num_classes > 1:
                if multiclass is False and implied_classes != num_classes:
                    raise ValueError(
                        "You have set `multiclass=False`, but the implied number of classes "
                        " (from shape of inputs) does not match `num_classes`."
                    )
                if target.size > 0 and num_classes <= int(np.asarray(target).max()):
                    raise ValueError("The highest label in `target` should be smaller than `num_classes`.")
                if preds.shape != target.shape and num_classes != implied_classes:
                    raise ValueError("The size of C dimension of `preds` does not match `num_classes`.")
        elif case == DataType.MULTILABEL:
            if multiclass and num_classes != 2:
                raise ValueError(
                    "Your have set `multiclass=True`, but `num_classes` is not equal to 2."
                    " If you are trying to transform multi-label data to 2 class multi-dimensional"
                    " multi-class, you should set `num_classes` to either 2 or None."
                )
            if not multiclass and num_classes != implied_classes:
                raise ValueError("The implied number of classes (from shape of inputs) does not match num_classes.")

    if top_k is not None:
        if case == DataType.BINARY:
            raise ValueError("You can not use `top_k` parameter with binary data.")
        if not isinstance(top_k, int) or top_k <= 0:
            raise ValueError("The `top_k` has to be an integer larger than 0.")
        if not _is_floating(preds):
            raise ValueError("You have set `top_k`, but you do not have probability predictions.")
        if multiclass is False:
            raise ValueError("If you set `multiclass=False`, you can not set `top_k`.")
        if case == DataType.MULTILABEL and multiclass:
            raise ValueError(
                "If you want to transform multi-label data to 2 class multi-dimensional"
                "multi-class data using `multiclass=True`, you can not use `top_k`."
            )
        if top_k >= implied_classes:
            raise ValueError("The `top_k` has to be strictly smaller than the `C` dimension of `preds`.")

    return case


def _input_format_classification(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, DataType]:
    """Convert preds/target into common one-hot format (reference: checks.py:313-452)."""
    if any(isinstance(x, jax.core.Tracer) for x in (preds, target)):
        raise NotImplementedError(
            "legacy-input metrics (Dice / old-style HingeLoss) classify their input"
            " mode from data VALUES (reference utilities/checks.py:206-452) and are"
            " eager-only; call update/compute outside jit"
        )
    preds, target = _input_squeeze(preds, target)
    if preds.dtype == jnp.float16:
        preds = preds.astype(jnp.float32)

    case = _check_classification_inputs(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
    )

    if case in (DataType.BINARY, DataType.MULTILABEL) and not top_k:
        preds = (preds >= threshold).astype(jnp.int32) if _is_floating(preds) else preds
        num_classes = num_classes if not multiclass else 2

    if case == DataType.MULTILABEL and top_k:
        preds = select_topk(preds, top_k)

    if case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) or multiclass:
        if _is_floating(preds):
            num_classes = preds.shape[1]
            preds = select_topk(preds, top_k or 1)
        else:
            num_classes = num_classes or int(
                max(int(np.asarray(preds).max()), int(np.asarray(target).max())) + 1
            )
            preds = to_onehot(preds, max(2, num_classes))
        target = to_onehot(target, max(2, num_classes))
        if multiclass is False:
            preds, target = preds[:, 1, ...], target[:, 1, ...]

    if preds.size > 0 or target.size > 0:
        if (case in (DataType.MULTICLASS, DataType.MULTIDIM_MULTICLASS) and multiclass is not False) or multiclass:
            target = target.reshape(target.shape[0], target.shape[1], -1)
            preds = preds.reshape(preds.shape[0], preds.shape[1], -1)
        else:
            target = target.reshape(target.shape[0], -1)
            preds = preds.reshape(preds.shape[0], -1)

    # torch .squeeze(-1) is a no-op on non-1 dims; mirror that
    if preds.ndim > 2 and preds.shape[-1] == 1:
        preds = preds.squeeze(-1)
    if target.ndim > 2 and target.shape[-1] == 1:
        target = target.squeeze(-1)

    return preds.astype(jnp.int32), target.astype(jnp.int32), case


def _del_column(data: Array, idx: int) -> Array:
    """Delete the column at index (reference: stat_scores.py:828-830)."""
    return jnp.concatenate([data[:, :idx], data[:, (idx + 1):]], axis=1)


def _drop_negative_ignored_indices(
    preds: Array, target: Array, ignore_index: int, mode: DataType
) -> Tuple[Array, Array]:
    """Remove negative ignored indices (reference: stat_scores.py:833-842)."""
    if mode == mode.MULTIDIM_MULTICLASS and _is_floating(preds):
        num_dims = len(preds.shape)
        preds = jnp.moveaxis(preds, 1, num_dims - 1)
        keep = np.asarray(target) != ignore_index
        preds = preds[keep]
        target = target[keep]
    elif mode in (mode.MULTICLASS, mode.MULTIDIM_MULTICLASS):
        keep = np.asarray(target) != ignore_index
        preds = preds[keep]
        target = target[keep]
    return preds, target


def _stat_scores(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
) -> Tuple[Array, Array, Array, Array]:
    """tp/fp/tn/fn from one-hot binary tensors (reference: stat_scores.py:845-889)."""
    dim: Union[int, Tuple[int, ...]] = 1  # for "samples"
    if reduce == "micro":
        dim = (0, 1) if preds.ndim == 2 else (1, 2)
    elif reduce == "macro":
        dim = 0 if preds.ndim == 2 else 2

    true_pred, false_pred = target == preds, target != preds
    pos_pred, neg_pred = preds == 1, preds == 0

    tp = (true_pred & pos_pred).sum(axis=dim)
    fp = (false_pred & pos_pred).sum(axis=dim)
    tn = (true_pred & neg_pred).sum(axis=dim)
    fn = (false_pred & neg_pred).sum(axis=dim)

    # int32 keeps the -1 sentinel exact; _count_dtype's float path is unnecessary here
    # because the legacy one-hot layout is capped well below 2^31 per update
    return (
        tp.astype(jnp.int32),
        fp.astype(jnp.int32),
        tn.astype(jnp.int32),
        fn.astype(jnp.int32),
    )


def _stat_scores_update(
    preds: Array,
    target: Array,
    reduce: Optional[str] = "micro",
    mdmc_reduce: Optional[str] = None,
    num_classes: Optional[int] = None,
    top_k: Optional[int] = 1,
    threshold: float = 0.5,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
    mode: Optional[DataType] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Legacy stat-scores update (reference: stat_scores.py:892-980)."""
    _negative_index_dropped = False
    if ignore_index is not None and ignore_index < 0 and mode is not None:
        preds, target = _drop_negative_ignored_indices(preds, target, ignore_index, mode)
        _negative_index_dropped = True

    preds, target, _ = _input_format_classification(
        preds,
        target,
        threshold=threshold,
        num_classes=num_classes,
        multiclass=multiclass,
        top_k=top_k,
        ignore_index=ignore_index,
    )

    if ignore_index is not None and ignore_index >= preds.shape[1]:
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {preds.shape[1]} classes")
    if ignore_index is not None and preds.shape[1] == 1:
        raise ValueError("You can not use `ignore_index` with binary data.")

    if preds.ndim == 3:
        if not mdmc_reduce:
            raise ValueError(
                "When your inputs are multi-dimensional multi-class, you have to set the `mdmc_reduce` parameter"
            )
        if mdmc_reduce == "global":
            preds = jnp.swapaxes(preds, 1, 2).reshape(-1, preds.shape[1])
            target = jnp.swapaxes(target, 1, 2).reshape(-1, target.shape[1])

    if ignore_index is not None and reduce != "macro" and not _negative_index_dropped:
        preds = _del_column(preds, ignore_index)
        target = _del_column(target, ignore_index)

    tp, fp, tn, fn = _stat_scores(preds, target, reduce=reduce)

    if ignore_index is not None and reduce == "macro" and not _negative_index_dropped:
        tp = tp.at[..., ignore_index].set(-1)
        fp = fp.at[..., ignore_index].set(-1)
        tn = tn.at[..., ignore_index].set(-1)
        fn = fn.at[..., ignore_index].set(-1)

    return tp, fp, tn, fn


def _reduce_stat_scores(
    numerator: Array,
    denominator: Array,
    weights: Optional[Array],
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Score reduction with zero-division/ignore masks (reference: stat_scores.py:1002-1056)."""
    numerator = jnp.asarray(numerator, jnp.float32)
    denominator = jnp.asarray(denominator, jnp.float32)
    zero_div_mask = denominator == 0
    ignore_mask = denominator < 0

    weights = jnp.ones_like(denominator) if weights is None else jnp.asarray(weights, jnp.float32)

    numerator = jnp.where(zero_div_mask, float(zero_division), numerator)
    denominator = jnp.where(zero_div_mask | ignore_mask, 1.0, denominator)
    weights = jnp.where(ignore_mask, 0.0, weights)

    if average not in (AverageMethod.MICRO, AverageMethod.NONE, None):
        weights = weights / weights.sum(axis=-1, keepdims=True)

    scores = weights * (numerator / denominator)
    scores = jnp.where(jnp.isnan(scores), float(zero_division), scores)

    if mdmc_average == MDMCAverageMethod.SAMPLEWISE:
        scores = scores.mean(axis=0)
        ignore_mask = ignore_mask.sum(axis=0).astype(bool)

    if average in (AverageMethod.NONE, None):
        return jnp.where(ignore_mask, jnp.nan, scores)
    return scores.sum()
