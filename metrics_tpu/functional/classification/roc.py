"""ROC curve functionals.

Capability parity with reference ``functional/classification/roc.py`` (508 LoC:
binary :40-158, multiclass :161-289, multilabel :292-420, dispatcher :423-508).
Shares the PR-curve state (binned (T,2,2) confusion tensor or raw scores).
"""
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_clf_curve,
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask
from metrics_tpu.utils.prints import rank_zero_warn


def _is_confmat_state(state) -> bool:
    return isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, (tuple, list))


def _binary_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Reference: roc.py:40-80."""
    if _is_confmat_state(state) and thresholds is not None:
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        tns = state[:, 0, 0]
        tpr = jnp.flip(_safe_divide(tps, tps + fns), 0)
        fpr = jnp.flip(_safe_divide(fps, fps + tns), 0)
        thresholds = jnp.flip(thresholds, 0)
        return fpr, tpr, thresholds

    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # under jit: static-shape padded device ROC (ops/clf_curve.py); the
        # first K rows are the reference curve, pads carry NaN thresholds
        from metrics_tpu.ops.clf_curve import binary_roc_curve_padded

        target = state[1] if pos_label == 1 else jnp.where(state[1] >= 0, (state[1] == pos_label).astype(jnp.int32), -1)
        fpr, tpr, thresholds, _ = binary_roc_curve_padded(state[0], target)
        return fpr, tpr, thresholds

    _p, _t = np.asarray(state[0]), np.asarray(state[1])
    keep = _t >= 0
    fps, tps, thresholds = _binary_clf_curve(_p[keep], _t[keep], pos_label=pos_label)
    tps = jnp.concatenate([jnp.zeros(1, dtype=tps.dtype), tps])
    fps = jnp.concatenate([jnp.zeros(1, dtype=fps.dtype), fps])
    thresholds = jnp.concatenate([jnp.ones(1, dtype=thresholds.dtype), thresholds])

    if float(fps[-1]) <= 0:
        rank_zero_warn(
            "No negative samples in targets, false positive value should be meaningless."
            " Returning zero tensor in false positive score",
            UserWarning,
        )
        fpr = jnp.zeros_like(thresholds)
    else:
        fpr = fps / fps[-1]

    if float(tps[-1]) <= 0:
        rank_zero_warn(
            "No positive samples in targets, true positive value should be meaningless."
            " Returning zero tensor in true positive score",
            UserWarning,
        )
        tpr = jnp.zeros_like(thresholds)
    else:
        tpr = tps / tps[-1]

    return fpr, tpr, thresholds


def binary_roc(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """Binary ROC (reference: roc.py:83-158)."""
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_roc_compute(state, thresholds)


def _multiclass_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference: roc.py:161-181."""
    if _is_confmat_state(state) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = jnp.flip(_safe_divide(tps, tps + fns), 0).T
        fpr = jnp.flip(_safe_divide(fps, fps + tns), 0).T
        thresholds = jnp.flip(thresholds, 0)
        return fpr, tpr, thresholds
    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # jit: one vmapped padded ROC kernel over the class axis (same shape
        # contract as the PR-curve traced branch)
        from metrics_tpu.ops.clf_curve import binary_roc_curve_padded

        def one_class(preds_c: Array, c: Array):
            target_c = jnp.where(state[1] >= 0, (state[1] == c).astype(jnp.int32), -1)
            return binary_roc_curve_padded(preds_c, target_c)

        fpr, tpr, thr, _ = jax.vmap(one_class, in_axes=(1, 0))(state[0], jnp.arange(num_classes))
        return fpr, tpr, thr

    fpr, tpr, thresholds_out = [], [], []
    for i in range(num_classes):
        res = _binary_roc_compute((state[0][:, i], state[1]), thresholds=None, pos_label=i)
        fpr.append(res[0])
        tpr.append(res[1])
        thresholds_out.append(res[2])
    return fpr, tpr, thresholds_out


def multiclass_roc(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Multiclass ROC (reference: roc.py:184-289)."""
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_roc_compute(state, num_classes, thresholds)


def _multilabel_roc_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference: roc.py:292-319."""
    if _is_confmat_state(state) and thresholds is not None:
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        tns = state[:, :, 0, 0]
        tpr = jnp.flip(_safe_divide(tps, tps + fns), 0).T
        fpr = jnp.flip(_safe_divide(fps, fps + tns), 0).T
        thresholds = jnp.flip(thresholds, 0)
        return fpr, tpr, thresholds
    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # jit: one vmapped padded ROC kernel over labels; target<0 rows
        # (ignore_index masks and buffer padding) are excluded by the kernel
        from metrics_tpu.ops.clf_curve import binary_roc_curve_padded

        fpr, tpr, thr, _ = jax.vmap(binary_roc_curve_padded, in_axes=(1, 1))(state[0], state[1])
        return fpr, tpr, thr

    fpr, tpr, thresholds_out = [], [], []
    for i in range(num_labels):
        preds_i = np.asarray(state[0][:, i])
        target_i = np.asarray(state[1][:, i])
        if ignore_index is not None:
            idx = target_i < 0
            preds_i = preds_i[~idx]
            target_i = target_i[~idx]
        res = _binary_roc_compute((preds_i, target_i), thresholds=None, pos_label=1)
        fpr.append(res[0])
        tpr.append(res[1])
        thresholds_out.append(res[2])
    return fpr, tpr, thresholds_out


def multilabel_roc(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Multilabel ROC (reference: roc.py:322-420)."""
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)


def roc(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference: roc.py:423-508)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_roc(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        return multiclass_roc(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_roc(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
