"""Calibration error functionals (reference: functional/classification/calibration_error.py).

TPU-first design: the reference bins confidences with ``torch.bucketize`` +
``scatter_add_`` (calibration_error.py:29-59). Here binning is a fused
``searchsorted`` + one-shot ``.at[].add`` scatter — a single XLA scatter kernel per
statistic, jit-safe with static ``n_bins``.
"""
from typing import Optional, Tuple, Union

import jax

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
)
from metrics_tpu.functional.classification.stat_scores import _is_floating, _softmax_if_logits
from metrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _binning_bucketize(
    confidences: Array, accuracies: Array, bin_boundaries: Array, valid: Optional[Array] = None
) -> Tuple[Array, Array, Array]:
    """Binned accuracy/confidence/proportion (reference: calibration_error.py:29-59).

    ``valid`` is an optional 0/1 mask: masked-out samples contribute zero weight, which
    is the jit-safe (static-shape) equivalent of the reference's ignore_index filtering.
    """
    accuracies = accuracies.astype(confidences.dtype)
    # len(bin_boundaries) bins (= n_bins+1): confidences exactly 1.0 land in a final
    # phantom bin, matching the reference's bucketize(right=True)-1 behavior exactly
    # (calibration_error.py:44-48; verified equal on saturated probabilities)
    n_bins = bin_boundaries.shape[0]
    indices = jnp.searchsorted(bin_boundaries, confidences, side="right") - 1
    indices = jnp.clip(indices, 0, n_bins - 1)
    weight = jnp.ones_like(confidences) if valid is None else valid.astype(confidences.dtype)

    count_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(weight)
    conf_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(confidences * weight)
    acc_bin = jnp.zeros(n_bins, dtype=confidences.dtype).at[indices].add(accuracies * weight)

    conf_bin = jnp.nan_to_num(conf_bin / count_bin)
    acc_bin = jnp.nan_to_num(acc_bin / count_bin)
    prop_bin = count_bin / count_bin.sum()
    return acc_bin, conf_bin, prop_bin


def _ce_compute(
    confidences: Array,
    accuracies: Array,
    bin_boundaries: Union[Array, int],
    norm: str = "l1",
    debias: bool = False,
    valid: Optional[Array] = None,
) -> Array:
    """Calibration error given bin boundaries and norm (reference: calibration_error.py:62-107)."""
    if isinstance(bin_boundaries, int):
        bin_boundaries = jnp.linspace(0.0, 1.0, bin_boundaries + 1, dtype=jnp.float32)
    if norm not in {"l1", "l2", "max"}:
        raise ValueError(f"Argument `norm` is expected to be one of 'l1', 'l2', 'max' but got {norm}")

    acc_bin, conf_bin, prop_bin = _binning_bucketize(confidences, accuracies, bin_boundaries, valid)

    if norm == "l1":
        return jnp.sum(jnp.abs(acc_bin - conf_bin) * prop_bin)
    if norm == "max":
        return jnp.max(jnp.abs(acc_bin - conf_bin))
    ce = jnp.sum(jnp.square(acc_bin - conf_bin) * prop_bin)
    if debias:
        debias_bins = (acc_bin * (acc_bin - 1) * prop_bin) / (prop_bin * accuracies.shape[0] - 1)
        ce = ce + jnp.sum(jnp.nan_to_num(debias_bins))
    return jnp.where(ce > 0, jnp.sqrt(jnp.maximum(ce, 0.0)), 0.0)


def _binary_calibration_error_arg_validation(
    n_bins: int,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(n_bins, int) or n_bins < 1:
        raise ValueError(f"Expected argument `n_bins` to be an integer larger than 0, but got {n_bins}")
    allowed_norm = ("l1", "l2", "max")
    if norm not in allowed_norm:
        raise ValueError(f"Expected argument `norm` to be one of {allowed_norm}, but got {norm}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_calibration_error_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _binary_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    confidences, accuracies = preds, target
    return confidences, accuracies


def binary_calibration_error(
    preds: Array,
    target: Array,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error for binary tasks (reference: calibration_error.py:140-208).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_calibration_error
        >>> preds = jnp.array([0.25, 0.25, 0.55, 0.75, 0.75])
        >>> target = jnp.array([0, 0, 1, 1, 1])
        >>> round(float(binary_calibration_error(preds, target, n_bins=2, norm='l1')), 4)
        0.29
    """
    if validate_args:
        _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)
        _binary_calibration_error_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(
        preds, target, threshold=0.0, ignore_index=ignore_index, convert_to_labels=False
    )
    valid = (jnp.asarray(target) >= 0) if ignore_index is not None else None
    confidences, accuracies = _binary_calibration_error_update(preds, jnp.maximum(target, 0))
    return _ce_compute(confidences, accuracies, n_bins, norm, valid=valid)


def _multiclass_calibration_error_arg_validation(
    num_classes: int,
    n_bins: int,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_calibration_error_arg_validation(n_bins, norm, ignore_index)


def _multiclass_calibration_error_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be floating tensor with probabilities/logits"
            f" but got tensor with dtype {preds.dtype}"
        )


def _multiclass_calibration_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Top-1 confidence + correctness (reference: calibration_error.py:235-244).

    Softmax-iff-logits is branchless (both paths traced, jnp.where on an
    all-reduction) so the update works under jit/shard_map — a host bool on
    traced data raised TracerBoolConversionError inside evaluate_sharded.

    Decision granularity: per update call eagerly, per SHARD under shard_map —
    like every probability/logit auto-detect in this package
    (_sigmoid_if_logits and friends). Identical results under the supported
    contract that one update's preds are homogeneous (all probabilities or
    all logits); a batch mixing the two is undefined either way.
    """
    preds = _softmax_if_logits(preds)
    confidences = preds.max(axis=1)
    predictions = preds.argmax(axis=1)
    accuracies = (predictions == target).astype(jnp.float32)
    return confidences.astype(jnp.float32), accuracies


def multiclass_calibration_error(
    preds: Array,
    target: Array,
    num_classes: int,
    n_bins: int = 15,
    norm: str = "l1",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error for multiclass tasks (reference: calibration_error.py:247-316).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multiclass_calibration_error
        >>> preds = jnp.array([[0.25, 0.20, 0.55],
        ...                    [0.55, 0.05, 0.40],
        ...                    [0.10, 0.30, 0.60],
        ...                    [0.90, 0.05, 0.05]])
        >>> target = jnp.array([0, 1, 2, 0])
        >>> round(float(multiclass_calibration_error(preds, target, num_classes=3, n_bins=3, norm='l1')), 4)
        0.2
    """
    if validate_args:
        _multiclass_calibration_error_arg_validation(num_classes, n_bins, norm, ignore_index)
        _multiclass_calibration_error_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index, convert_to_labels=False)
    valid = (jnp.asarray(target) >= 0) if ignore_index is not None else None
    confidences, accuracies = _multiclass_calibration_error_update(preds, target)
    return _ce_compute(confidences, accuracies, n_bins, norm, valid=valid)


def calibration_error(
    preds: Array,
    target: Array,
    task: str,
    n_bins: int = 15,
    norm: str = "l1",
    num_classes: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Top-label calibration error dispatcher (reference: calibration_error.py:319-384)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_calibration_error(preds, target, n_bins, norm, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_calibration_error(preds, target, num_classes, n_bins, norm, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
