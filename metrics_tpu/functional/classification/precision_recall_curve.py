"""Precision-recall curve kernels — the second shared core of classification.

Capability parity with reference ``functional/classification/precision_recall_curve.py``
(922 LoC: _binary_clf_curve :28-80, binary :94-350, multiclass :353-635, multilabel
:638-860, dispatcher :863-922). Two state modes, as in the reference:

- ``thresholds=None`` (exact): store all preds/targets (cat states), compute the curve
  at unique thresholds via sort+cumsum. Output size is data-dependent, so this path is
  **host-side** (numpy) at compute time — matching the reference's eager behavior.
- ``thresholds=int/list/array`` (binned): constant-memory multi-threshold confusion
  tensor ``(T, 2, 2)``. TPU-first redesign: instead of the reference's
  bincount-of-mapping (:205-219) or python loop over thresholds (:222-243), the
  confusion entries are **fused broadcast-compare reductions**
  (``(preds[:,None] >= thr) & target[:,None] -> sum over N``) — XLA fuses the N x T
  intermediate into the reduction (no materialization, no scatter), which vectorizes on
  the VPU and shards cleanly under GSPMD. No 50k-element vectorize-vs-loop switch is
  needed (:198-202) — the fused form is both the fast and the low-memory path.
"""
from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _is_floating, _sigmoid_if_logits, _softmax_if_logits
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask


def _binary_clf_curve(
    preds: Array,
    target: Array,
    sample_weights: Optional[Union[Array, list]] = None,
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """fps/tps at every unique threshold, sklearn-style (reference: :28-80).

    Host-side: output length is data-dependent (number of distinct scores).
    """
    preds = np.asarray(preds)
    target = np.asarray(target)
    if sample_weights is not None:
        sample_weights = np.asarray(sample_weights, dtype=np.float32)

    if preds.ndim > target.ndim:
        preds = preds[:, 0]
    desc_score_indices = np.argsort(preds, kind="stable")[::-1]
    preds = preds[desc_score_indices]
    target = target[desc_score_indices]
    weight = sample_weights[desc_score_indices] if sample_weights is not None else 1.0

    distinct_value_indices = np.where(preds[1:] - preds[:-1])[0]
    threshold_idxs = np.concatenate([distinct_value_indices, [target.size - 1]])
    target = (target == pos_label).astype(np.int64)
    tps = np.cumsum(target * weight, axis=0)[threshold_idxs]

    if sample_weights is not None:
        fps = np.cumsum((1 - target) * weight, axis=0)[threshold_idxs]
    else:
        fps = 1 + threshold_idxs - tps

    return jnp.asarray(fps), jnp.asarray(tps), jnp.asarray(preds[threshold_idxs])


def _adjust_threshold_arg(
    thresholds: Optional[Union[int, List[float], Array]] = None, device=None
) -> Optional[Array]:
    """int/list/array thresholds -> 1d array (reference: :83-91)."""
    if isinstance(thresholds, int):
        return jnp.linspace(0, 1, thresholds)
    if isinstance(thresholds, list):
        return jnp.asarray(thresholds)
    if thresholds is not None:
        return jnp.asarray(thresholds)
    return None


def _binary_precision_recall_curve_arg_validation(
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    if thresholds is not None and not isinstance(thresholds, (list, int, jnp.ndarray, np.ndarray)):
        raise ValueError(
            "Expected argument `thresholds` to either be an integer, list of floats or"
            f" tensor of floats, but got {thresholds}"
        )
    if isinstance(thresholds, int) and thresholds < 2:
        raise ValueError(
            f"If argument `thresholds` is an integer, expected it to be larger than 1, but got {thresholds}"
        )
    if isinstance(thresholds, list) and not all(isinstance(t, float) and 0 <= t <= 1 for t in thresholds):
        raise ValueError(
            f"If argument `thresholds` is a list, expected all elements to be floats in the [0,1] range, "
            f"but got {thresholds}"
        )
    if isinstance(thresholds, (jnp.ndarray, np.ndarray)):
        if np.asarray(thresholds).ndim != 1:
            raise ValueError("If argument `thresholds` is an tensor, expected the tensor to be 1d")
        if not bool(np.all((np.asarray(thresholds) >= 0) & (np.asarray(thresholds) <= 1))):
            raise ValueError("If argument `thresholds` is an tensor, expected all elements to be in [0,1] range")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")


def _binary_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    if preds.shape != target.shape:
        raise ValueError(
            "Expected `preds` and `target` to have the same shape,"
            f" but got `preds` with shape={preds.shape} and `target` with shape={target.shape}"
        )
    if jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating):
        raise ValueError(
            "Expected argument `target` to be an int or long tensor with ground truth labels"
            f" but got tensor with dtype {target.dtype}"
        )
    if not _is_floating(preds):
        raise ValueError(
            "Expected argument `preds` to be an floating tensor with probability/logit scores,"
            f" but got tensor with dtype {preds.dtype}"
        )
    if not _is_concrete(preds, target):
        return
    unique_values = np.unique(np.asarray(target))
    if ignore_index is None:
        check = np.any((unique_values != 0) & (unique_values != 1))
    else:
        check = np.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index))
    if check:
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [0, 1, ignore_index]}."
        )


def _binary_precision_recall_curve_format(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """Flatten, sigmoid-if-logits; ignored targets -> -1 (masked in update)."""
    preds = jnp.asarray(preds).ravel()
    target = jnp.asarray(target).ravel()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)

    preds = _sigmoid_if_logits(preds)

    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds


def _binary_precision_recall_curve_update(
    preds: Array,
    target: Array,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (T,2,2) confusion tensor via fused broadcast reductions; exact: passthrough."""
    if thresholds is None:
        return preds, target
    preds_t = preds[:, None] >= thresholds[None, :]  # (N, T) — fused into the sums below
    t1 = (target == 1)[:, None]
    t0 = (target == 0)[:, None]
    tp = (preds_t & t1).sum(0)
    fp = (preds_t & t0).sum(0)
    fn = ((~preds_t) & t1).sum(0)
    tn = ((~preds_t) & t0).sum(0)
    return jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)  # (T, 2, 2)


def _binary_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    pos_label: int = 1,
) -> Tuple[Array, Array, Array]:
    """Final curve from confusion tensor (binned) or raw scores (exact). Reference: :246-272."""
    if isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, (tuple, list)):
        tps = state[:, 1, 1]
        fps = state[:, 0, 1]
        fns = state[:, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones(1, dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros(1, dtype=recall.dtype)])
        return precision, recall, thresholds

    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # under jit: static-shape padded device curve (ops/clf_curve.py). The
        # first K = (~isnan(thresholds)).sum() entries are the reference curve;
        # precision/recall pads repeat the final (1, 0) point.
        from metrics_tpu.ops.clf_curve import binary_precision_recall_curve_padded

        target = state[1] if pos_label == 1 else jnp.where(state[1] >= 0, (state[1] == pos_label).astype(jnp.int32), -1)
        precision, recall, thresholds, _ = binary_precision_recall_curve_padded(state[0], target)
        return precision, recall, thresholds

    # exact mode is host-side; drop positions masked to -1 by ignore_index
    _p, _t = np.asarray(state[0]), np.asarray(state[1])
    keep = _t >= 0
    fps, tps, thresholds = _binary_clf_curve(_p[keep], _t[keep], pos_label=pos_label)
    precision = tps / (tps + fps)
    recall = tps / tps[-1]

    precision = jnp.concatenate([jnp.flip(precision, 0), jnp.ones(1, dtype=precision.dtype)])
    recall = jnp.concatenate([jnp.flip(recall, 0), jnp.zeros(1, dtype=recall.dtype)])
    thresholds = jnp.flip(thresholds, 0)
    return precision, recall, thresholds


def binary_precision_recall_curve(
    preds: Array,
    target: Array,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array, Array]:
    """Precision-recall curve for binary tasks (reference: :275-350).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_precision_recall_curve
        >>> preds = jnp.array([0, 0.5, 0.7, 0.8])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> prec, rec, thr = binary_precision_recall_curve(preds, target, thresholds=5)
        >>> prec
        Array([0.5      , 0.6666667, 0.6666667, 0.       , 0.       , 1.       ],      dtype=float32)
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_precision_recall_curve_compute(state, thresholds)


# -------------------------------------------------------------------- multiclass


def _multiclass_precision_recall_curve_arg_validation(
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)


def _multiclass_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if not preds.ndim == target.ndim + 1:
        raise ValueError(
            f"Expected `preds` to have one more dimension than `target` but got {preds.ndim} and {target.ndim}"
        )
    if jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating):
        raise ValueError(
            f"Expected argument `target` to be an int or long tensor, but got tensor with dtype {target.dtype}"
        )
    if not _is_floating(preds):
        raise ValueError(f"Expected `preds` to be a float tensor, but got {preds.dtype}")
    if preds.shape[1] != num_classes:
        raise ValueError(
            "Expected `preds.shape[1]` to be equal to the number of classes but"
            f" got {preds.shape[1]} and {num_classes}."
        )
    if preds.shape[0] != target.shape[0] or preds.shape[2:] != target.shape[1:]:
        raise ValueError(
            "Expected the shape of `preds` should be (N, C, ...) and the shape of `target` should be (N, ...)"
            f" but got {preds.shape} and {target.shape}"
        )
    if not _is_concrete(preds, target):
        return
    num_unique_values = len(np.unique(np.asarray(target)))
    check = num_unique_values > num_classes if ignore_index is None else num_unique_values > num_classes + 1
    if check:
        raise RuntimeError(
            "Detected more unique values in `target` than `num_classes`. Expected only "
            f"{num_classes if ignore_index is None else num_classes + 1} but found "
            f"{num_unique_values} in `target`."
        )


def _multiclass_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """(N, C, ...) -> (N', C) probs + (N',) labels; ignored targets -> -1."""
    preds = jnp.moveaxis(jnp.asarray(preds), 0, 1).reshape(num_classes, -1).T
    target = jnp.asarray(target).ravel()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)

    preds = _softmax_if_logits(preds)

    thresholds = _adjust_threshold_arg(thresholds)
    return preds, target, thresholds


def _multiclass_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (T,C,2,2) confusion tensor via fused broadcast reductions."""
    if thresholds is None:
        return preds, target
    valid = (target >= 0)[:, None, None]
    preds_t = preds[:, :, None] >= thresholds[None, None, :]  # (N, C, T)
    target_oh = jax.nn.one_hot(target, num_classes, dtype=bool)[:, :, None]  # (N, C, 1)
    tp = (preds_t & target_oh & valid).sum(0)
    fp = (preds_t & (~target_oh) & valid).sum(0)
    fn = ((~preds_t) & target_oh & valid).sum(0)
    tn = ((~preds_t) & (~target_oh) & valid).sum(0)
    # (C, T) each -> (T, C, 2, 2)
    confmat = jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)
    return jnp.moveaxis(confmat, 0, 1)


def _multiclass_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference: :510-535."""
    if isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, (tuple, list)):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_classes), dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros((1, num_classes), dtype=recall.dtype)])
        return precision.T, recall.T, thresholds

    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # jit: ONE batched sort pipeline over the class axis instead of C traced
        # kernels (vmap of the padded device curve, see ops/clf_curve.py)
        from metrics_tpu.ops.clf_curve import binary_precision_recall_curve_padded

        def one_class(preds_c: Array, c: Array) -> Tuple[Array, Array, Array, Array]:
            target_c = jnp.where(state[1] >= 0, (state[1] == c).astype(jnp.int32), -1)
            return binary_precision_recall_curve_padded(preds_c, target_c)

        prec, rec, thr, _ = jax.vmap(one_class, in_axes=(1, 0))(state[0], jnp.arange(num_classes))
        return prec, rec, thr

    precision, recall, thresholds_out = [], [], []
    for i in range(num_classes):
        res = _binary_precision_recall_curve_compute((state[0][:, i], state[1]), thresholds=None, pos_label=i)
        precision.append(res[0])
        recall.append(res[1])
        thresholds_out.append(res[2])
    return precision, recall, thresholds_out


def multiclass_precision_recall_curve(
    preds: Array,
    target: Array,
    num_classes: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Precision-recall curve for multiclass tasks (reference: :538-635)."""
    if validate_args:
        _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)


# -------------------------------------------------------------------- multilabel


def _multilabel_precision_recall_curve_arg_validation(
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)


def _multilabel_precision_recall_curve_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    if preds.shape[1] != num_labels:
        raise ValueError(
            "Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )


def _multilabel_precision_recall_curve_format(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> Tuple[Array, Array, Optional[Array]]:
    """(N, C, ...) -> (N', L); ignored positions -> target=-1 (masked in update)."""
    preds = jnp.moveaxis(jnp.asarray(preds), 0, 1).reshape(num_labels, -1).T
    target = jnp.moveaxis(jnp.asarray(target), 0, 1).reshape(num_labels, -1).T
    preds = _sigmoid_if_logits(preds)

    thresholds = _adjust_threshold_arg(thresholds)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target, thresholds


def _multilabel_precision_recall_curve_update(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Array],
) -> Union[Array, Tuple[Array, Array]]:
    """Binned: (T,L,2,2) via fused broadcast reductions with validity masking."""
    if thresholds is None:
        return preds, target
    valid = (target >= 0)[:, :, None]
    preds_t = preds[:, :, None] >= thresholds[None, None, :]  # (N, L, T)
    t1 = (target == 1)[:, :, None]
    t0 = (target == 0)[:, :, None]
    tp = (preds_t & t1 & valid).sum(0)
    fp = (preds_t & t0 & valid).sum(0)
    fn = ((~preds_t) & t1 & valid).sum(0)
    tn = ((~preds_t) & t0 & valid).sum(0)
    confmat = jnp.stack([jnp.stack([tn, fp], -1), jnp.stack([fn, tp], -1)], -2)  # (L, T, 2, 2)
    return jnp.moveaxis(confmat, 0, 1)


def _multilabel_precision_recall_curve_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Reference: :726-760."""
    if isinstance(state, (jnp.ndarray, np.ndarray)) and not isinstance(state, (tuple, list)):
        tps = state[:, :, 1, 1]
        fps = state[:, :, 0, 1]
        fns = state[:, :, 1, 0]
        precision = _safe_divide(tps, tps + fps)
        recall = _safe_divide(tps, tps + fns)
        precision = jnp.concatenate([precision, jnp.ones((1, num_labels), dtype=precision.dtype)])
        recall = jnp.concatenate([recall, jnp.zeros((1, num_labels), dtype=recall.dtype)])
        return precision.T, recall.T, thresholds

    if not _is_concrete(state[0]) or not _is_concrete(state[1]):
        # jit: one vmapped padded kernel over labels; it masks target<0 itself
        # (both ignore_index positions and buffer padding carry -1)
        from metrics_tpu.ops.clf_curve import binary_precision_recall_curve_padded

        prec, rec, thr, _ = jax.vmap(binary_precision_recall_curve_padded, in_axes=(1, 1))(state[0], state[1])
        return prec, rec, thr

    precision, recall, thresholds_out = [], [], []
    for i in range(num_labels):
        # target<0 rows (ignore_index masks) are dropped by the callee's host path
        res = _binary_precision_recall_curve_compute(
            (np.asarray(state[0][:, i]), np.asarray(state[1][:, i])), thresholds=None, pos_label=1
        )
        precision.append(res[0])
        recall.append(res[1])
        thresholds_out.append(res[2])
    return precision, recall, thresholds_out


def multilabel_precision_recall_curve(
    preds: Array,
    target: Array,
    num_labels: int,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array, Array], Tuple[List[Array], List[Array], List[Array]]]:
    """Precision-recall curve for multilabel tasks (reference: :763-860)."""
    if validate_args:
        _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)


def precision_recall_curve(
    preds: Array,
    target: Array,
    task: str,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
):
    """Task dispatcher (reference: :863-922)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_precision_recall_curve(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_precision_recall_curve(preds, target, num_classes, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_precision_recall_curve(preds, target, num_labels, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
