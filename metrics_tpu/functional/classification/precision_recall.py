"""Precision / recall functionals.

Capability parity with reference ``functional/classification/precision_recall.py``
(_precision_recall_reduce :38-61, binary/multiclass/multilabel precision :64-366,
recall :369-672, dispatchers :675-729).
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_pipeline,
)
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask


def _precision_recall_reduce(
    stat: str,
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
) -> Array:
    """Reference: functional/classification/precision_recall.py:38-61."""
    different_stat = fp if stat == "precision" else fn
    if average == "binary":
        return _safe_divide(tp, tp + different_stat)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        _sum = lambda x: x.sum(axis=axis) if x.ndim > axis else x
        tp = _sum(tp)
        different_stat = _sum(different_stat)
        return _safe_divide(tp, tp + different_stat)

    score = _safe_divide(tp, tp + different_stat)
    if average is None or average == "none":
        return score
    weights = (tp + fn).astype(score.dtype) if average == "weighted" else jnp.ones_like(score)
    return _safe_divide(weights * score, weights.sum(-1, keepdims=True)).sum(-1)


def binary_precision(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:64-140."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:143-246."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:249-366."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("precision", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def binary_recall(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:369-444."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_recall(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:447-550."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_recall(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/precision_recall.py:553-672."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args
    )
    return _precision_recall_reduce("recall", tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def precision(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Dispatcher (reference: functional/classification/precision_recall.py:675-729)."""
    task = ClassificationTask.from_str(task)
    assert multidim_average is not None
    if task == ClassificationTask.BINARY:
        return binary_precision(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        assert isinstance(top_k, int)
        return multiclass_precision(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_precision(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


def recall(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Dispatcher (reference: functional/classification/precision_recall.py:732-786)."""
    task = ClassificationTask.from_str(task)
    assert multidim_average is not None
    if task == ClassificationTask.BINARY:
        return binary_recall(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        assert isinstance(top_k, int)
        return multiclass_recall(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_recall(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
