"""F-beta / F1 functionals.

Capability parity with reference ``functional/classification/f_beta.py``
(_fbeta_reduce :38-61, fbeta :74-378, f1 :381-663, dispatchers :664-770).
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_pipeline,
)
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask


def _fbeta_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    beta: float,
    average: Optional[str],
    multidim_average: str = "global",
) -> Array:
    """Reference: functional/classification/f_beta.py:38-61."""
    beta2 = beta**2
    if average == "binary":
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        _sum = lambda x: x.sum(axis=axis) if x.ndim > axis else x
        tp, fn, fp = _sum(tp), _sum(fn), _sum(fp)
        return _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)

    fbeta_score = _safe_divide((1 + beta2) * tp, (1 + beta2) * tp + beta2 * fn + fp)
    if average is None or average == "none":
        return fbeta_score
    weights = (tp + fn).astype(fbeta_score.dtype) if average == "weighted" else jnp.ones_like(fbeta_score)
    return _safe_divide(weights * fbeta_score, weights.sum(-1, keepdims=True)).sum(-1)


def _binary_fbeta_score_arg_validation(
    beta: float,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)


def binary_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:74-145."""
    if validate_args:
        _binary_fbeta_score_arg_validation(beta, threshold, multidim_average, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average="binary", multidim_average=multidim_average)


def _multiclass_fbeta_score_arg_validation(
    beta: float,
    num_classes: int,
    top_k: int = 1,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)


def multiclass_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:161-264."""
    if validate_args:
        _multiclass_fbeta_score_arg_validation(beta, num_classes, top_k, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average)


def _multilabel_fbeta_score_arg_validation(
    beta: float,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
) -> None:
    if not (isinstance(beta, float) and beta > 0):
        raise ValueError(f"Expected argument `beta` to be a float larger than 0, but got {beta}.")
    _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)


def multilabel_fbeta_score(
    preds: Array,
    target: Array,
    beta: float,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:280-378."""
    if validate_args:
        _multilabel_fbeta_score_arg_validation(beta, num_labels, threshold, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args
    )
    return _fbeta_reduce(tp, fp, tn, fn, beta, average=average, multidim_average=multidim_average)


def binary_f1_score(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:381-452."""
    return binary_fbeta_score(preds, target, 1.0, threshold, multidim_average, ignore_index, validate_args)


def multiclass_f1_score(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:455-558."""
    return multiclass_fbeta_score(
        preds, target, 1.0, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )


def multilabel_f1_score(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/f_beta.py:561-663."""
    return multilabel_fbeta_score(
        preds, target, 1.0, num_labels, threshold, average, multidim_average, ignore_index, validate_args
    )


def fbeta_score(
    preds: Array,
    target: Array,
    task: str,
    beta: float = 1.0,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Dispatcher (reference: functional/classification/f_beta.py:664-714)."""
    task = ClassificationTask.from_str(task)
    assert multidim_average is not None
    if task == ClassificationTask.BINARY:
        return binary_fbeta_score(preds, target, beta, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        assert isinstance(top_k, int)
        return multiclass_fbeta_score(
            preds, target, beta, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_fbeta_score(
            preds, target, beta, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


def f1_score(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Dispatcher (reference: functional/classification/f_beta.py:717-770)."""
    return fbeta_score(
        preds,
        target,
        task,
        1.0,
        threshold,
        num_classes,
        num_labels,
        average,
        multidim_average,
        top_k,
        ignore_index,
        validate_args,
    )
