"""Recall-at-fixed-precision functionals (reference: functional/classification/recall_fixed_precision.py)."""
from typing import Callable, List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.compute import _smallest_f32_at_least
from metrics_tpu.utils.enums import ClassificationTask


def _lexicographic_best(
    primary: Array, secondary: Array, thresholds: Array, min_secondary: float
) -> Tuple[Array, Array]:
    """max over (primary, secondary, threshold) tuples where secondary >= min_secondary.

    Eager path: host-side selection mirroring the reference's
    ``max((r, p, t) for ... if p >= min_precision)`` (recall_fixed_precision.py:40-55).
    Traced path: the same lexicographic max expressed branchlessly — a cascade of
    masked maxes (best primary, then best secondary among primary-ties, then best
    threshold among (primary, secondary)-ties) — so the fixed-point metrics
    (recall@precision / precision@recall / specificity@sensitivity) compute inside
    jit/shard_map. Values on both paths live on the f32 grid, so the comparisons
    (including ``>= min_secondary`` against the f64 constant) decide identically.
    """
    if not _is_concrete(primary, secondary, thresholds):
        n = min(primary.shape[0], secondary.shape[0], thresholds.shape[0])
        p, s, t = primary[:n], secondary[:n], thresholds[:n]
        cutoff = _smallest_f32_at_least(min_secondary)  # f64-equivalent compare on the f32 grid
        # padded exact-mode curves mark their pad rows with NaN thresholds; the
        # host path never sees pad rows, so they must not qualify here either
        ok = (s >= cutoff) & ~jnp.isnan(t)
        neg = -jnp.inf
        best_p = jnp.max(jnp.where(ok, p, neg), initial=neg)
        tie_p = ok & (p == best_p)
        best_s = jnp.max(jnp.where(tie_p, s, neg), initial=neg)
        best_t = jnp.max(jnp.where(tie_p & (s == best_s), t, neg), initial=neg)
        any_ok = jnp.any(ok)
        best_primary = jnp.where(any_ok, best_p, 0.0).astype(jnp.float32)
        best_threshold = jnp.where(any_ok, best_t, 0.0).astype(jnp.float32)
        # the reference pins the threshold to 1e6 whenever the best value is 0
        best_threshold = jnp.where(best_primary == 0.0, jnp.float32(1e6), best_threshold)
        return best_primary, best_threshold

    p = np.asarray(primary, dtype=np.float64)
    s = np.asarray(secondary, dtype=np.float64)
    t = np.asarray(thresholds, dtype=np.float64)
    triples = [(p[i], s[i], t[i]) for i in range(min(len(t), len(p), len(s))) if s[i] >= min_secondary]
    if not triples:
        best_primary, best_threshold = 0.0, 0.0
    else:
        best_primary, _, best_threshold = max(triples)
    if best_primary == 0.0:
        best_threshold = 1e6
    return jnp.asarray(best_primary, dtype=jnp.float32), jnp.asarray(best_threshold, dtype=jnp.float32)


def _recall_at_precision(
    precision: Array,
    recall: Array,
    thresholds: Array,
    min_precision: float,
) -> Tuple[Array, Array]:
    """Reference: recall_fixed_precision.py:40-55 (max recall s.t. precision >= min)."""
    return _lexicographic_best(recall, precision, thresholds, min_precision)


def _binary_recall_at_fixed_precision_arg_validation(
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _binary_recall_at_fixed_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    min_precision: float,
    pos_label: int = 1,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    precision, recall, thresholds = _binary_precision_recall_curve_compute(state, thresholds, pos_label)
    return reduce_fn(precision, recall, thresholds, min_precision)


def binary_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest recall given minimum precision, binary (reference: recall_fixed_precision.py:73-166).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_recall_at_fixed_precision
        >>> preds = jnp.array([0, 0.5, 0.7, 0.8])
        >>> target = jnp.array([0, 1, 1, 0])
        >>> binary_recall_at_fixed_precision(preds, target, min_precision=0.5, thresholds=5)
        (Array(1., dtype=float32), Array(0.5, dtype=float32))
    """
    if validate_args:
        _binary_recall_at_fixed_precision_arg_validation(min_precision, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_recall_at_fixed_precision_compute(state, thresholds, min_precision)


def _multiclass_recall_at_fixed_precision_arg_validation(
    num_classes: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _multiclass_recall_at_fixed_precision_arg_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    min_precision: float,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    """Reference: recall_fixed_precision.py:169-183."""
    precision, recall, thresholds = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    if not isinstance(precision, list) and getattr(thresholds, "ndim", 1) != 2:
        # binned: one shared 1-D threshold grid for every class
        res = [reduce_fn(p, r, thresholds, min_precision) for p, r in zip(precision, recall)]
    else:
        # exact: per-class threshold rows — lists eagerly, stacked 2-D from the
        # jit path (the guard keeps rows paired with their class's thresholds;
        # the reduce runs branchlessly on device when traced, host numpy when not)
        res = [reduce_fn(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multiclass_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest recall given minimum precision, multiclass (reference: recall_fixed_precision.py:186-262)."""
    if validate_args:
        _multiclass_recall_at_fixed_precision_arg_validation(num_classes, min_precision, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_recall_at_fixed_precision_arg_compute(state, num_classes, thresholds, min_precision)


def _multilabel_recall_at_fixed_precision_arg_validation(
    num_labels: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if not isinstance(min_precision, float) or not (0 <= min_precision <= 1):
        raise ValueError(
            f"Expected argument `min_precision` to be an float in the [0,1] range, but got {min_precision}"
        )


def _multilabel_recall_at_fixed_precision_arg_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int],
    min_precision: float,
    reduce_fn: Callable = _recall_at_precision,
) -> Tuple[Array, Array]:
    """Reference: recall_fixed_precision.py:278-295."""
    precision, recall, thresholds = _multilabel_precision_recall_curve_compute(
        state, num_labels, thresholds, ignore_index
    )
    if not isinstance(precision, list) and getattr(thresholds, "ndim", 1) != 2:
        # binned: one shared 1-D threshold grid for every class
        res = [reduce_fn(p, r, thresholds, min_precision) for p, r in zip(precision, recall)]
    else:
        # exact: per-class threshold rows — lists eagerly, stacked 2-D from the
        # jit path (the guard keeps rows paired with their class's thresholds;
        # the reduce runs branchlessly on device when traced, host numpy when not)
        res = [reduce_fn(p, r, t, min_precision) for p, r, t in zip(precision, recall, thresholds)]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multilabel_recall_at_fixed_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest recall given minimum precision, multilabel (reference: recall_fixed_precision.py:298-377)."""
    if validate_args:
        _multilabel_recall_at_fixed_precision_arg_validation(num_labels, min_precision, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_recall_at_fixed_precision_arg_compute(state, num_labels, thresholds, ignore_index, min_precision)


def recall_at_fixed_precision(
    preds: Array,
    target: Array,
    task: str,
    min_precision: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array], Tuple[List[Array], List[Array]]]:
    """Dispatcher (reference: recall_fixed_precision.py:380-428)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_recall_at_fixed_precision(preds, target, min_precision, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_recall_at_fixed_precision(
            preds, target, num_classes, min_precision, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_recall_at_fixed_precision(
            preds, target, num_labels, min_precision, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
