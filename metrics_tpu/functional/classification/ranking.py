"""Multilabel ranking functionals (reference: functional/classification/ranking.py).

TPU-first design: the reference loops over samples for label-ranking average precision
(ranking.py:251-268) using ``torch.unique``-based tie ranks. Here ranks-with-ties are
computed as fully-vectorized pairwise comparison sums over the (small) label axis:
``rank(x_j) = #{k : x_k <= x_j}`` — an O(N*C^2) batched matmul-shaped kernel that maps
onto the MXU, with no host loop and no data-dependent shapes.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
)
from metrics_tpu.functional.classification.stat_scores import _is_floating


def _rank_data(x: Array) -> Array:
    """Rank with ties resolved to the max rank of the tie group (reference: ranking.py:27-33).

    ``_rank_data(x)[j] = #{k : x_k <= x_j}`` — matches the reference's
    unique+cumsum-of-counts formulation without data-dependent shapes.
    """
    return (x[None, :] <= x[:, None]).sum(axis=1)


def _ranking_reduce(score: Array, n_elements: Array) -> Array:
    return score / n_elements


def _multilabel_ranking_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    if not _is_floating(preds):
        raise ValueError(f"Expected preds tensor to be floating point, but received input with dtype {preds.dtype}")


def _multilabel_coverage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Accumulate state for coverage error (reference: ranking.py:48-55)."""
    offset = jnp.where(target == 0, jnp.abs(preds.min()) + 10, 0.0)
    preds_mod = preds + offset
    preds_min = preds_mod.min(axis=1)
    coverage = (preds >= preds_min[:, None]).sum(axis=1).astype(jnp.float32)
    return coverage.sum(), coverage.size


def multilabel_coverage_error(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel coverage error (reference: ranking.py:58-108).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multilabel_coverage_error
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (10, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(1), (10, 5), 0, 2)
        >>> float(multilabel_coverage_error(preds, target, num_labels=5)) > 0
        True
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    coverage, total = _multilabel_coverage_error_update(preds, target)
    return _ranking_reduce(coverage, total)


def _multilabel_ranking_average_precision_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Label-ranking AP state (reference: ranking.py:251-268), vectorized.

    For each sample: over relevant labels, mean of
    (rank among relevant of -pred) / (rank among all of -pred); 1.0 when no relevant
    labels or all labels relevant.
    """
    neg_preds = -preds
    n_preds, n_labels = neg_preds.shape
    relevant = target == 1

    # rank(x_j) = #{k: x_k <= x_j}; relevant-only ranks mask the comparison set
    le = neg_preds[:, None, :] <= neg_preds[:, :, None]  # (N, C, C): le[i, j, k] = x_k <= x_j
    rank_all = le.sum(axis=2).astype(jnp.float32)
    rank_rel = (le & relevant[:, None, :]).sum(axis=2).astype(jnp.float32)

    n_relevant = relevant.sum(axis=1)
    per_label = jnp.where(relevant, rank_rel / rank_all, 0.0)
    score_idx = jnp.where(n_relevant > 0, per_label.sum(axis=1) / jnp.maximum(n_relevant, 1), 1.0)
    score_idx = jnp.where((n_relevant > 0) & (n_relevant < n_labels), score_idx, 1.0)
    return score_idx.sum(), n_preds


def multilabel_ranking_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking average precision for multilabel data (reference: ranking.py:271-321).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multilabel_ranking_average_precision
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (10, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(1), (10, 5), 0, 2)
        >>> 0 <= float(multilabel_ranking_average_precision(preds, target, num_labels=5)) <= 1
        True
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    score, n_elements = _multilabel_ranking_average_precision_update(preds, target)
    return _ranking_reduce(score, n_elements)


def _multilabel_ranking_loss_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    """Label ranking loss state (reference: ranking.py:184-209), vectorized with masks
    instead of boolean-filtered shapes."""
    n_preds, n_labels = preds.shape
    relevant = target == 1
    n_relevant = relevant.sum(axis=1)
    mask = (n_relevant > 0) & (n_relevant < n_labels)

    inverse = jnp.argsort(jnp.argsort(preds, axis=1), axis=1)
    per_label_loss = ((n_labels - inverse) * relevant).astype(jnp.float32)
    correction = 0.5 * n_relevant * (n_relevant + 1)
    denom = n_relevant * (n_labels - n_relevant)
    loss = (per_label_loss.sum(axis=1) - correction) / jnp.maximum(denom, 1)
    loss = jnp.where(mask, loss, 0.0)
    return loss.sum(), n_preds


def multilabel_ranking_loss(
    preds: Array,
    target: Array,
    num_labels: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Label ranking loss for multilabel data (reference: ranking.py:212-263).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multilabel_ranking_loss
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (10, 5))
        >>> target = jax.random.randint(jax.random.PRNGKey(1), (10, 5), 0, 2)
        >>> float(multilabel_ranking_loss(preds, target, num_labels=5)) >= 0
        True
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold=0.0, ignore_index=ignore_index)
        _multilabel_ranking_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(
        preds, target, num_labels, threshold=0.0, ignore_index=ignore_index, should_threshold=False
    )
    loss, n_elements = _multilabel_ranking_loss_update(preds, target)
    return _ranking_reduce(loss, n_elements)
