"""Dice functional (reference: functional/classification/dice.py)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification._legacy import (
    _input_squeeze,
    _reduce_stat_scores,
    _stat_scores_update,
)
from metrics_tpu.utils.enums import AverageMethod, MDMCAverageMethod


def _dice_compute(
    tp: Array,
    fp: Array,
    fn: Array,
    average: Optional[str],
    mdmc_average: Optional[str],
    zero_division: int = 0,
) -> Array:
    """Dice from stat scores (reference: dice.py:24-64)."""
    numerator = 2 * tp
    denominator = 2 * tp + fp + fn

    if average == AverageMethod.MACRO and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        cond = tp + fp + fn == 0
        import numpy as np

        keep = ~np.asarray(cond)
        numerator = numerator[keep]
        denominator = denominator[keep]

    if average == AverageMethod.NONE and mdmc_average != MDMCAverageMethod.SAMPLEWISE:
        # a class is not present if there exists no TPs, no FPs, and no FNs
        meaningless = (tp | fn | fp) == 0
        numerator = jnp.where(meaningless, -1, numerator)
        denominator = jnp.where(meaningless, -1, denominator)

    return _reduce_stat_scores(
        numerator=numerator,
        denominator=denominator,
        weights=None if average != "weighted" else tp + fn,
        average=average,
        mdmc_average=mdmc_average,
        zero_division=zero_division,
    )


def dice(
    preds: Array,
    target: Array,
    zero_division: int = 0,
    average: Optional[str] = "micro",
    mdmc_average: Optional[str] = "global",
    threshold: float = 0.5,
    top_k: Optional[int] = None,
    num_classes: Optional[int] = None,
    multiclass: Optional[bool] = None,
    ignore_index: Optional[int] = None,
) -> Array:
    """Dice score (reference: dice.py:67-208).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import dice
        >>> preds = jnp.array([2, 0, 2, 1])
        >>> target = jnp.array([1, 1, 2, 0])
        >>> dice(preds, target, average='micro')
        Array(0.25, dtype=float32)
    """
    allowed_average = ("micro", "macro", "weighted", "samples", "none", None)
    if average not in allowed_average:
        raise ValueError(f"The `average` has to be one of {allowed_average}, got {average}.")

    if average in ("macro", "weighted", "none", None) and (not num_classes or num_classes < 1):
        raise ValueError(f"When you set `average` as {average}, you have to provide the number of classes.")

    allowed_mdmc_average = [None, "samplewise", "global"]
    if mdmc_average not in allowed_mdmc_average:
        raise ValueError(f"The `mdmc_average` has to be one of {allowed_mdmc_average}, got {mdmc_average}.")

    if num_classes and ignore_index is not None and (not ignore_index < num_classes or num_classes == 1):
        raise ValueError(f"The `ignore_index` {ignore_index} is not valid for inputs with {num_classes} classes")

    if top_k is not None and (not isinstance(top_k, int) or top_k <= 0):
        raise ValueError(f"The `top_k` should be an integer larger than 0, got {top_k}")

    preds, target = _input_squeeze(preds, target)
    reduce = "macro" if average in ("weighted", "none", None) else average
    tp, fp, tn, fn = _stat_scores_update(
        preds,
        target,
        reduce=reduce,
        mdmc_reduce=mdmc_average,
        threshold=threshold,
        num_classes=num_classes,
        top_k=top_k,
        multiclass=multiclass,
        ignore_index=ignore_index,
    )
    return _dice_compute(tp, fp, fn, average, mdmc_average, zero_division)
