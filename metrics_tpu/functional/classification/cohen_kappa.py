"""Cohen's kappa functionals.

Capability parity with reference ``functional/classification/cohen_kappa.py``
(_cohen_kappa_reduce :33-55, binary :75-140, multiclass :160-230, dispatcher :233-280).
"""
from typing import Any, Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
)
from metrics_tpu.utils.enums import ClassificationTaskNoMultilabel


def _cohen_kappa_reduce(confmat: Array, weights: Optional[str] = None) -> Array:
    """(C,C) confusion matrix -> kappa score (reference: :33-55)."""
    confmat = confmat.astype(jnp.float32)
    n_classes = confmat.shape[0]
    sum0 = confmat.sum(axis=0, keepdims=True)
    sum1 = confmat.sum(axis=1, keepdims=True)
    expected = sum1 @ sum0 / sum0.sum()

    if weights is None or weights == "none":
        w_mat = 1.0 - jnp.eye(n_classes, dtype=confmat.dtype)
    elif weights in ("linear", "quadratic"):
        idx = jnp.arange(n_classes, dtype=confmat.dtype)
        diff = idx[:, None] - idx[None, :]
        w_mat = jnp.abs(diff) if weights == "linear" else diff**2
    else:
        raise ValueError(
            f"Received {weights} for argument ``weights`` but should be either None, 'linear' or 'quadratic'"
        )
    k = jnp.sum(w_mat * confmat) / jnp.sum(w_mat * expected)
    return 1 - k


def _binary_cohen_kappa_arg_validation(
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    weights: Optional[str] = None,
) -> None:
    _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
    if weights not in ("linear", "quadratic", "none", None):
        raise ValueError(
            f"Expected argument `weight` to be one of ('linear', 'quadratic', 'none', None), but got {weights}."
        )


def binary_cohen_kappa(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary Cohen's kappa (reference: :75-140).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_cohen_kappa
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> binary_cohen_kappa(preds, target)
        Array(0.5, dtype=float32)
    """
    if validate_args:
        _binary_cohen_kappa_arg_validation(threshold, ignore_index, weights)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _cohen_kappa_reduce(confmat, weights)


def _multiclass_cohen_kappa_arg_validation(
    num_classes: int,
    ignore_index: Optional[int] = None,
    weights: Optional[str] = None,
) -> None:
    _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
    if weights not in ("linear", "quadratic", "none", None):
        raise ValueError(
            f"Expected argument `weight` to be one of ('linear', 'quadratic', 'none', None), but got {weights}."
        )


def multiclass_cohen_kappa(
    preds: Array,
    target: Array,
    num_classes: int,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass Cohen's kappa (reference: :160-230)."""
    if validate_args:
        _multiclass_cohen_kappa_arg_validation(num_classes, ignore_index, weights)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _cohen_kappa_reduce(confmat, weights)


def cohen_kappa(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    weights: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: :233-280)."""
    task = ClassificationTaskNoMultilabel.from_str(task)
    if task == ClassificationTaskNoMultilabel.BINARY:
        return binary_cohen_kappa(preds, target, threshold, weights, ignore_index, validate_args)
    if task == ClassificationTaskNoMultilabel.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_cohen_kappa(preds, target, num_classes, weights, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
