"""Hamming distance functionals.

Capability parity with reference ``functional/classification/hamming.py``
(_hamming_distance_reduce :38-87, binary :90-160, multiclass :163-266,
multilabel :269-372, dispatcher :375-429).
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_pipeline,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_pipeline,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_pipeline,
)
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask


def _hamming_distance_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reference: functional/classification/hamming.py:38-87."""
    if average == "binary":
        return 1 - _safe_divide(tp + tn, tp + fp + tn + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        _sum = lambda x: x.sum(axis=axis) if x.ndim > axis else x
        tp, fn = _sum(tp), _sum(fn)
        if multilabel:
            fp, tn = _sum(fp), _sum(tn)
            return 1 - _safe_divide(tp + tn, tp + tn + fp + fn)
        return 1 - _safe_divide(tp, tp + fn)

    score = 1 - _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else 1 - _safe_divide(tp, tp + fn)
    if average is None or average == "none":
        return score
    weights = (tp + fn).astype(score.dtype) if average == "weighted" else jnp.ones_like(score)
    return _safe_divide(weights * score, weights.sum(-1, keepdims=True)).sum(-1)


def binary_hamming_distance(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/hamming.py:90-160."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_pipeline(
        preds, target, threshold, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_hamming_distance(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/hamming.py:163-266."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_pipeline(
        preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_hamming_distance(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Reference: functional/classification/hamming.py:269-372."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_pipeline(
        preds, target, num_labels, threshold, multidim_average, ignore_index, validate_args
    )
    return _hamming_distance_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def hamming_distance(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Dispatcher (reference: functional/classification/hamming.py:375-429)."""
    task = ClassificationTask.from_str(task)
    assert multidim_average is not None
    if task == ClassificationTask.BINARY:
        return binary_hamming_distance(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        assert isinstance(top_k, int)
        return multiclass_hamming_distance(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_hamming_distance(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
