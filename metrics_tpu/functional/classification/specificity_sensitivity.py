"""Specificity-at-sensitivity functionals (reference: functional/classification/specificity_sensitivity.py)."""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import (
    _binary_roc_compute,
    _multiclass_roc_compute,
    _multilabel_roc_compute,
)
from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.compute import _smallest_f32_at_least
from metrics_tpu.utils.enums import ClassificationTask


def _convert_fpr_to_specificity(fpr: Array) -> Array:
    """Reference: specificity_sensitivity.py:42-44."""
    return 1 - fpr


def _specificity_at_sensitivity(
    specificity: Array,
    sensitivity: Array,
    thresholds: Array,
    min_sensitivity: float,
) -> Tuple[Array, Array]:
    """Max specificity with sensitivity >= min (reference: specificity_sensitivity.py:47-70).

    Unlike the recall/precision fixed-point reduce, the reference picks the FIRST
    argmax row (no lexicographic threshold tie-break) and applies the 1e6 sentinel
    only when no row qualifies. The traced branch reproduces exactly that with a
    masked argmax (jnp.argmax also returns the first maximum), so the metric
    computes inside jit; eager keeps the host numpy selection.
    """
    if not _is_concrete(specificity, sensitivity, thresholds):
        cutoff = _smallest_f32_at_least(min_sensitivity)  # f64-equivalent compare on the f32 grid
        # NaN thresholds mark pad rows of the padded exact-mode curves; the host
        # path never sees pad rows, so they must not qualify here either
        ok = (sensitivity >= cutoff) & ~jnp.isnan(thresholds)
        masked = jnp.where(ok, specificity, -jnp.inf)
        idx = jnp.argmax(masked)  # first max among qualifying rows, original order
        any_ok = jnp.any(ok)
        best_spec = jnp.where(any_ok, specificity[idx], 0.0).astype(jnp.float32)
        best_thr = jnp.where(any_ok, thresholds[idx], jnp.float32(1e6)).astype(jnp.float32)
        return best_spec, best_thr

    spec = np.asarray(specificity, dtype=np.float64)
    sens = np.asarray(sensitivity, dtype=np.float64)
    thr = np.asarray(thresholds, dtype=np.float64)
    indices = sens >= min_sensitivity
    if not indices.any():
        return jnp.asarray(0.0, dtype=jnp.float32), jnp.asarray(1e6, dtype=jnp.float32)
    spec, thr = spec[indices], thr[indices]
    idx = int(np.argmax(spec))
    return jnp.asarray(spec[idx], dtype=jnp.float32), jnp.asarray(thr[idx], dtype=jnp.float32)


def _binary_specificity_at_sensitivity_arg_validation(
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _binary_specificity_at_sensitivity_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    min_sensitivity: float,
    pos_label: int = 1,
) -> Tuple[Array, Array]:
    """Reference: specificity_sensitivity.py:84-93."""
    fpr, sensitivity, thresholds = _binary_roc_compute(state, thresholds, pos_label)
    specificity = _convert_fpr_to_specificity(fpr)
    return _specificity_at_sensitivity(specificity, sensitivity, thresholds, min_sensitivity)


def binary_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest specificity given minimum sensitivity, binary (reference: specificity_sensitivity.py:96-170).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_specificity_at_sensitivity
        >>> preds = jnp.array([0, 0.5, 0.4, 0.1])
        >>> target = jnp.array([0, 1, 1, 1])
        >>> binary_specificity_at_sensitivity(preds, target, min_sensitivity=0.5, thresholds=5)
        (Array(1., dtype=float32), Array(0.25, dtype=float32))
    """
    if validate_args:
        _binary_specificity_at_sensitivity_arg_validation(min_sensitivity, thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_specificity_at_sensitivity_compute(state, thresholds, min_sensitivity)


def _multiclass_specificity_at_sensitivity_arg_validation(
    num_classes: int,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _multiclass_specificity_at_sensitivity_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    thresholds: Optional[Array],
    min_sensitivity: float,
) -> Tuple[Array, Array]:
    """Reference: specificity_sensitivity.py:184-201."""
    fpr, sensitivity, thresholds = _multiclass_roc_compute(state, num_classes, thresholds)
    if isinstance(fpr, list) or getattr(thresholds, "ndim", 1) == 2:
        # per-class threshold rows: lists eagerly, stacked 2-D from the exact-mode
        # jit path (same pairing guard as recall_fixed_precision.py)
        specificity = [_convert_fpr_to_specificity(f) for f in fpr]
        res = [
            _specificity_at_sensitivity(sp, sn, t, min_sensitivity)
            for sp, sn, t in zip(specificity, sensitivity, thresholds)
        ]
    else:
        specificity = _convert_fpr_to_specificity(fpr)
        res = [
            _specificity_at_sensitivity(sp, sn, thresholds, min_sensitivity)
            for sp, sn in zip(specificity, sensitivity)
        ]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multiclass_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_classes: int,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest specificity given minimum sensitivity, multiclass (reference: specificity_sensitivity.py:204-288)."""
    if validate_args:
        _multiclass_specificity_at_sensitivity_arg_validation(num_classes, min_sensitivity, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_specificity_at_sensitivity_compute(state, num_classes, thresholds, min_sensitivity)


def _multilabel_specificity_at_sensitivity_arg_validation(
    num_labels: int,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if not isinstance(min_sensitivity, float) or not (0 <= min_sensitivity <= 1):
        raise ValueError(
            f"Expected argument `min_sensitivity` to be an float in the [0,1] range, but got {min_sensitivity}"
        )


def _multilabel_specificity_at_sensitivity_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    thresholds: Optional[Array],
    ignore_index: Optional[int],
    min_sensitivity: float,
) -> Tuple[Array, Array]:
    """Reference: specificity_sensitivity.py:302-320."""
    fpr, sensitivity, thresholds = _multilabel_roc_compute(state, num_labels, thresholds, ignore_index)
    if isinstance(fpr, list) or getattr(thresholds, "ndim", 1) == 2:
        # per-label threshold rows: lists eagerly, stacked 2-D from the exact-mode
        # jit path (same pairing guard as recall_fixed_precision.py)
        specificity = [_convert_fpr_to_specificity(f) for f in fpr]
        res = [
            _specificity_at_sensitivity(sp, sn, t, min_sensitivity)
            for sp, sn, t in zip(specificity, sensitivity, thresholds)
        ]
    else:
        specificity = _convert_fpr_to_specificity(fpr)
        res = [
            _specificity_at_sensitivity(sp, sn, thresholds, min_sensitivity)
            for sp, sn in zip(specificity, sensitivity)
        ]
    return jnp.stack([r[0] for r in res]), jnp.stack([r[1] for r in res])


def multilabel_specificity_at_sensitivity(
    preds: Array,
    target: Array,
    num_labels: int,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Tuple[Array, Array]:
    """Highest specificity given minimum sensitivity, multilabel (reference: specificity_sensitivity.py:323-401)."""
    if validate_args:
        _multilabel_specificity_at_sensitivity_arg_validation(num_labels, min_sensitivity, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_specificity_at_sensitivity_compute(state, num_labels, thresholds, ignore_index, min_sensitivity)


def specicity_at_sensitivity(
    preds: Array,
    target: Array,
    task: str,
    min_sensitivity: float,
    thresholds: Optional[Union[int, List[float], Array]] = None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Union[Tuple[Array, Array], Tuple[List[Array], List[Array]]]:
    """Dispatcher; the reference public name carries this typo (specificity_sensitivity.py:404)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_specificity_at_sensitivity(
            preds, target, min_sensitivity, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_specificity_at_sensitivity(
            preds, target, num_classes, min_sensitivity, thresholds, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_specificity_at_sensitivity(
            preds, target, num_labels, min_sensitivity, thresholds, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")


specificity_at_sensitivity = specicity_at_sensitivity
