"""Matthews correlation coefficient functionals.

Capability parity with reference ``functional/classification/matthews_corrcoef.py``
(_matthews_corrcoef_reduce :37-54, binary :57-107, multiclass :110-165, multilabel
:168-226, dispatcher :229-280).
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import (
    _binary_confusion_matrix_arg_validation,
    _binary_confusion_matrix_format,
    _binary_confusion_matrix_tensor_validation,
    _binary_confusion_matrix_update,
    _multiclass_confusion_matrix_arg_validation,
    _multiclass_confusion_matrix_format,
    _multiclass_confusion_matrix_tensor_validation,
    _multiclass_confusion_matrix_update,
    _multilabel_confusion_matrix_arg_validation,
    _multilabel_confusion_matrix_format,
    _multilabel_confusion_matrix_tensor_validation,
    _multilabel_confusion_matrix_update,
)
from metrics_tpu.utils.enums import ClassificationTask


def _matthews_corrcoef_reduce(confmat: Array) -> Array:
    """Confusion matrix -> MCC (reference: :37-54); 0/0 -> 0, branchless."""
    confmat = confmat.sum(0) if confmat.ndim == 3 else confmat  # multilabel -> binary
    tk = confmat.sum(axis=-1).astype(jnp.float32)
    pk = confmat.sum(axis=-2).astype(jnp.float32)
    c = jnp.trace(confmat).astype(jnp.float32)
    s = confmat.sum().astype(jnp.float32)

    cov_ytyp = c * s - jnp.sum(tk * pk)
    cov_ypyp = s**2 - jnp.sum(pk * pk)
    cov_ytyt = s**2 - jnp.sum(tk * tk)

    denom = cov_ypyp * cov_ytyt
    return jnp.where(denom == 0, 0.0, cov_ytyp / jnp.sqrt(jnp.where(denom == 0, 1.0, denom)))


def binary_matthews_corrcoef(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary MCC (reference: :57-107).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_matthews_corrcoef
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> binary_matthews_corrcoef(preds, target)
        Array(0.57735026, dtype=float32)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize=None)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _matthews_corrcoef_reduce(confmat)


def multiclass_matthews_corrcoef(
    preds: Array,
    target: Array,
    num_classes: int,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass MCC (reference: :110-165)."""
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize=None)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _matthews_corrcoef_reduce(confmat)


def multilabel_matthews_corrcoef(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel MCC (reference: :168-226)."""
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize=None)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, num_labels)
    return _matthews_corrcoef_reduce(confmat)


def matthews_corrcoef(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: :229-280)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_matthews_corrcoef(preds, target, threshold, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        return multiclass_matthews_corrcoef(preds, target, num_classes, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_matthews_corrcoef(preds, target, num_labels, threshold, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
