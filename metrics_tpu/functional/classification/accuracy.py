"""Accuracy functionals.

Capability parity with reference ``functional/classification/accuracy.py``
(_accuracy_reduce :38-89, binary :92-163, multiclass :166-270, multilabel :273-371,
dispatcher :374-440). All reductions are jit-safe.
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
    _binary_stat_scores_update,
    _multiclass_stat_scores_arg_validation,
    _multiclass_stat_scores_format_update,
    _multiclass_stat_scores_tensor_validation,
    _multilabel_stat_scores_arg_validation,
    _multilabel_stat_scores_format,
    _multilabel_stat_scores_tensor_validation,
    _multilabel_stat_scores_update,
)
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask


def _accuracy_reduce(
    tp: Array,
    fp: Array,
    tn: Array,
    fn: Array,
    average: Optional[str],
    multidim_average: str = "global",
    multilabel: bool = False,
) -> Array:
    """Reduce stat scores into accuracy (reference: functional/classification/accuracy.py:38-89)."""
    if average == "binary":
        return _safe_divide(tp + tn, tp + tn + fp + fn)
    if average == "micro":
        axis = 0 if multidim_average == "global" else 1
        _sum = lambda x: x.sum(axis=axis) if x.ndim > axis else x
        tp = _sum(tp)
        fn = _sum(fn)
        if multilabel:
            fp = _sum(fp)
            tn = _sum(tn)
            return _safe_divide(tp + tn, tp + tn + fp + fn)
        return _safe_divide(tp, tp + fn)

    score = _safe_divide(tp + tn, tp + tn + fp + fn) if multilabel else _safe_divide(tp, tp + fn)
    if average is None or average == "none":
        return score
    weights = (tp + fn).astype(score.dtype) if average == "weighted" else jnp.ones_like(score)
    return _safe_divide(weights * score, weights.sum(-1, keepdims=True)).sum(-1)


def binary_accuracy(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary accuracy (reference: functional/classification/accuracy.py:92-163)."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, multidim_average, ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, multidim_average, ignore_index)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    tp, fp, tn, fn = _binary_stat_scores_update(preds, target, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average="binary", multidim_average=multidim_average)


def multiclass_accuracy(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    top_k: int = 1,
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass accuracy (reference: functional/classification/accuracy.py:166-270)."""
    if validate_args:
        _multiclass_stat_scores_arg_validation(num_classes, top_k, average, multidim_average, ignore_index)
        _multiclass_stat_scores_tensor_validation(preds, target, num_classes, multidim_average, ignore_index)
    tp, fp, tn, fn = _multiclass_stat_scores_format_update(
        preds, target, num_classes, top_k, average, multidim_average, ignore_index
    )
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average)


def multilabel_accuracy(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    average: Optional[str] = "macro",
    multidim_average: str = "global",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel accuracy (reference: functional/classification/accuracy.py:273-371)."""
    if validate_args:
        _multilabel_stat_scores_arg_validation(num_labels, threshold, average, multidim_average, ignore_index)
        _multilabel_stat_scores_tensor_validation(preds, target, num_labels, multidim_average, ignore_index)
    preds, target = _multilabel_stat_scores_format(preds, target, num_labels, threshold, ignore_index)
    tp, fp, tn, fn = _multilabel_stat_scores_update(preds, target, multidim_average)
    return _accuracy_reduce(tp, fp, tn, fn, average=average, multidim_average=multidim_average, multilabel=True)


def accuracy(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "micro",
    multidim_average: Optional[str] = "global",
    top_k: Optional[int] = 1,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: functional/classification/accuracy.py:374-440)."""
    task = ClassificationTask.from_str(task)
    assert multidim_average is not None
    if task == ClassificationTask.BINARY:
        return binary_accuracy(preds, target, threshold, multidim_average, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        if not isinstance(top_k, int):
            raise ValueError(f"`top_k` is expected to be `int` but `{type(top_k)} was passed.`")
        return multiclass_accuracy(
            preds, target, num_classes, average, top_k, multidim_average, ignore_index, validate_args
        )
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_accuracy(
            preds, target, num_labels, threshold, average, multidim_average, ignore_index, validate_args
        )
    raise ValueError(f"Not handled value: {task}")
