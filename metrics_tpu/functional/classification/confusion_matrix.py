"""Confusion-matrix kernels.

Capability parity with reference ``functional/classification/confusion_matrix.py``
(646 LoC: reduce :26-58, binary :61-221, multiclass :224-460, multilabel :463-588,
dispatcher :591-646), re-designed jit-safe:

- ignore_index masks positions to ``-1`` and the update drops them via a weighted
  bincount (weight 0) instead of boolean-index filtering (dynamic shapes) — the same
  negative-mapping trick the reference itself uses for multilabel (:509-510).
- The bincount lowers to one XLA scatter-add with a static ``length``; for GSPMD
  (sharded inputs under jit) run under ``jax.set_mesh`` so the scatter output sharding
  resolves.
"""
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.stat_scores import _is_floating, _sigmoid_if_logits
from metrics_tpu.utils.checks import _check_same_shape, _is_concrete
from metrics_tpu.ops.confmat import confusion_counts
from metrics_tpu.utils.data import _bincount_weighted
from metrics_tpu.utils.enums import ClassificationTask
from metrics_tpu.utils.prints import rank_zero_warn


def _confusion_matrix_reduce(confmat: Array, normalize: Optional[str] = None) -> Array:
    """Normalize a confusion matrix (reference: :26-58). NaN (0/0 rows) -> 0."""
    allowed_normalize = ("true", "pred", "all", "none", None)
    if normalize not in allowed_normalize:
        raise ValueError(f"Argument `normalize` needs to one of the following: {allowed_normalize}")
    if normalize is not None and normalize != "none":
        confmat = confmat.astype(jnp.float32)
        if normalize == "true":
            confmat = confmat / confmat.sum(axis=-1, keepdims=True)
        elif normalize == "pred":
            confmat = confmat / confmat.sum(axis=-2, keepdims=True)
        elif normalize == "all":
            confmat = confmat / confmat.sum(axis=(-2, -1), keepdims=True)
        confmat = jnp.nan_to_num(confmat, nan=0.0)
    return confmat


def _masked_confmat_bins(mapping: Array, valid: Array, length: int) -> Array:
    """Weighted bincount of ``mapping`` where ``valid``; ignored entries weight 0."""
    mapping = jnp.clip(mapping, 0, length - 1).astype(jnp.int32)
    return _bincount_weighted(mapping, valid.astype(jnp.float32), minlength=length).astype(jnp.int32)


# ----------------------------------------------------------------------- binary


def _binary_confusion_matrix_arg_validation(
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(
            f"Expected argument `normalize` to be one of ('true', 'pred', 'all', 'none', None), but got {normalize}."
        )


def _binary_confusion_matrix_tensor_validation(
    preds: Array, target: Array, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if not _is_concrete(preds, target):
        return
    unique_values = np.unique(np.asarray(target))
    if ignore_index is None:
        check = np.any((unique_values != 0) & (unique_values != 1))
    else:
        check = np.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index))
    if check:
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [0, 1, ignore_index]}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if np.any((unique_values != 0) & (unique_values != 1)):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )


def _binary_confusion_matrix_format(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> Tuple[Array, Array]:
    """Flatten + sigmoid/threshold; ignored targets -> -1 (reference: :115-143)."""
    preds = jnp.asarray(preds).ravel()
    target = jnp.asarray(target).ravel()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        if convert_to_labels:
            preds = (preds > threshold).astype(jnp.int32)
    return preds, target


def _binary_confusion_matrix_update(preds: Array, target: Array) -> Array:
    """2x2 bins via masked bincount (reference: :145-150)."""
    mapping = target * 2 + preds
    return _masked_confmat_bins(mapping, target >= 0, 4).reshape(2, 2)


def _binary_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def binary_confusion_matrix(
    preds: Array,
    target: Array,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """2x2 confusion matrix for binary tasks (reference: :162-221).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_confusion_matrix
        >>> target = jnp.array([1, 1, 0, 0])
        >>> preds = jnp.array([0, 1, 0, 0])
        >>> binary_confusion_matrix(preds, target)
        Array([[2, 0],
               [1, 1]], dtype=int32)
    """
    if validate_args:
        _binary_confusion_matrix_arg_validation(threshold, ignore_index, normalize)
        _binary_confusion_matrix_tensor_validation(preds, target, ignore_index)
    preds, target = _binary_confusion_matrix_format(preds, target, threshold, ignore_index)
    confmat = _binary_confusion_matrix_update(preds, target)
    return _binary_confusion_matrix_compute(confmat, normalize)


# -------------------------------------------------------------------- multiclass


def _multiclass_confusion_matrix_arg_validation(
    num_classes: int,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    if not isinstance(num_classes, int) or num_classes < 2:
        raise ValueError(f"Expected argument `num_classes` to be an integer larger than 1, but got {num_classes}")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(
            f"Expected argument `normalize` to be one of ('true', 'pred', 'all', 'none', None), but got {normalize}."
        )


def _multiclass_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_classes: int, ignore_index: Optional[int] = None
) -> None:
    if preds.ndim == target.ndim + 1:
        if not _is_floating(preds):
            raise ValueError("If `preds` have one dimension more than `target`, `preds` should be a float tensor.")
        if preds.shape[1] != num_classes:
            raise ValueError(
                "If `preds` have one dimension more than `target`, `preds.shape[1]` should be"
                " equal to number of classes."
            )
        if preds.shape[2:] != target.shape[1:]:
            raise ValueError(
                "If `preds` have one dimension more than `target`, the shape of `preds` should be"
                " (N, C, ...), and the shape of `target` should be (N, ...)."
            )
    elif preds.ndim == target.ndim:
        if preds.shape != target.shape:
            raise ValueError(
                "The `preds` and `target` should have the same shape,"
                f" got `preds` with shape={preds.shape} and `target` with shape={target.shape}."
            )
    else:
        raise ValueError(
            "Either `preds` and `target` both should have the (same) shape (N, ...), or `target` should be (N, ...)"
            " and `preds` should be (N, C, ...)."
        )
    if not _is_concrete(preds, target):
        return
    num_unique_values = len(np.unique(np.asarray(target)))
    check = num_unique_values > num_classes if ignore_index is None else num_unique_values > num_classes + 1
    if check:
        raise RuntimeError(
            "Detected more unique values in `target` than `num_classes`. Expected only"
            f" {num_classes if ignore_index is None else num_classes + 1} but found"
            f" {num_unique_values} in `target`."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if len(unique_values) > num_classes:
            raise RuntimeError(
                "Detected more unique values in `preds` than `num_classes`. Expected only"
                f" {num_classes} but found {len(unique_values)} in `preds`."
            )


def _multiclass_confusion_matrix_format(
    preds: Array,
    target: Array,
    ignore_index: Optional[int] = None,
    convert_to_labels: bool = True,
) -> Tuple[Array, Array]:
    """Argmax + flatten; ignored targets -> -1 (reference: :298-321)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.ndim == target.ndim + 1 and convert_to_labels:
        preds = preds.argmax(axis=1)
    preds = preds.ravel() if convert_to_labels else jnp.moveaxis(preds, 1, -1).reshape(-1, preds.shape[1])
    target = target.ravel()
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _multiclass_confusion_matrix_update(preds: Array, target: Array, num_classes: int) -> Array:
    """CxC counts (reference: :324-328) — bincount or one-hot-MXU-matmul tier
    (ops/confmat.py) depending on class count and platform."""
    return confusion_counts(preds, target, target >= 0, num_classes)


def _multiclass_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multiclass_confusion_matrix(
    preds: Array,
    target: Array,
    num_classes: int,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """CxC confusion matrix for multiclass tasks (reference: :400-460).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multiclass_confusion_matrix
        >>> target = jnp.array([2, 1, 0, 0])
        >>> preds = jnp.array([2, 1, 0, 1])
        >>> multiclass_confusion_matrix(preds, target, num_classes=3)
        Array([[1, 1, 0],
               [0, 1, 0],
               [0, 0, 1]], dtype=int32)
    """
    if validate_args:
        _multiclass_confusion_matrix_arg_validation(num_classes, ignore_index, normalize)
        _multiclass_confusion_matrix_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target = _multiclass_confusion_matrix_format(preds, target, ignore_index)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _multiclass_confusion_matrix_compute(confmat, normalize)


# -------------------------------------------------------------------- multilabel


def _multilabel_confusion_matrix_arg_validation(
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    normalize: Optional[str] = None,
) -> None:
    if not isinstance(num_labels, int) or num_labels < 2:
        raise ValueError(f"Expected argument `num_labels` to be an integer larger than 1, but got {num_labels}")
    if not (isinstance(threshold, float) and (0 <= threshold <= 1)):
        raise ValueError(f"Expected argument `threshold` to be a float in the [0,1] range, but got {threshold}.")
    if ignore_index is not None and not isinstance(ignore_index, int):
        raise ValueError(f"Expected argument `ignore_index` to either be `None` or an integer, but got {ignore_index}")
    if normalize not in ("true", "pred", "all", "none", None):
        raise ValueError(
            f"Expected argument `normalize` to be one of ('true', 'pred', 'all', 'none', None), but got {normalize}."
        )


def _multilabel_confusion_matrix_tensor_validation(
    preds: Array, target: Array, num_labels: int, ignore_index: Optional[int] = None
) -> None:
    _check_same_shape(preds, target)
    if preds.shape[1] != num_labels:
        raise ValueError(
            f"Expected both `target.shape[1]` and `preds.shape[1]` to be equal to the number of labels"
            f" but got {preds.shape[1]} and expected {num_labels}"
        )
    if not _is_concrete(preds, target):
        return
    unique_values = np.unique(np.asarray(target))
    if ignore_index is None:
        check = np.any((unique_values != 0) & (unique_values != 1))
    else:
        check = np.any((unique_values != 0) & (unique_values != 1) & (unique_values != ignore_index))
    if check:
        raise RuntimeError(
            f"Detected the following values in `target`: {unique_values} but expected only"
            f" the following values {[0, 1] if ignore_index is None else [0, 1, ignore_index]}."
        )
    if not _is_floating(preds):
        unique_values = np.unique(np.asarray(preds))
        if np.any((unique_values != 0) & (unique_values != 1)):
            raise RuntimeError(
                f"Detected the following values in `preds`: {unique_values} but expected only"
                " the following values [0,1] since preds is a label tensor."
            )


def _multilabel_confusion_matrix_format(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    should_threshold: bool = True,
) -> Tuple[Array, Array]:
    """Sigmoid/threshold + reshape (-1, L); ignored targets -> -1 (reference: :473-504)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if _is_floating(preds):
        preds = _sigmoid_if_logits(preds)
        if should_threshold:
            preds = (preds > threshold).astype(jnp.int32)
    preds = jnp.moveaxis(preds, 1, -1).reshape(-1, num_labels)
    target = jnp.moveaxis(target, 1, -1).reshape(-1, num_labels)
    if ignore_index is not None:
        target = jnp.where(target == ignore_index, -1, target)
    return preds, target


def _multilabel_confusion_matrix_update(preds: Array, target: Array, num_labels: int) -> Array:
    """(L,2,2) bins via masked bincount (reference: :507-512)."""
    mapping = 2 * target + preds + 4 * jnp.arange(num_labels)
    return _masked_confmat_bins(mapping, target >= 0, 4 * num_labels).reshape(num_labels, 2, 2)


def _multilabel_confusion_matrix_compute(confmat: Array, normalize: Optional[str] = None) -> Array:
    return _confusion_matrix_reduce(confmat, normalize)


def multilabel_confusion_matrix(
    preds: Array,
    target: Array,
    num_labels: int,
    threshold: float = 0.5,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """(L,2,2) confusion matrices for multilabel tasks (reference: :525-588).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import multilabel_confusion_matrix
        >>> target = jnp.array([[0, 1, 0], [1, 0, 1]])
        >>> preds = jnp.array([[0, 0, 1], [1, 0, 1]])
        >>> multilabel_confusion_matrix(preds, target, num_labels=3)
        Array([[[1, 0],
                [0, 1]],
        <BLANKLINE>
               [[1, 0],
                [1, 0]],
        <BLANKLINE>
               [[0, 1],
                [0, 1]]], dtype=int32)
    """
    if validate_args:
        _multilabel_confusion_matrix_arg_validation(num_labels, threshold, ignore_index, normalize)
        _multilabel_confusion_matrix_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target = _multilabel_confusion_matrix_format(preds, target, num_labels, threshold, ignore_index)
    confmat = _multilabel_confusion_matrix_update(preds, target, num_labels)
    return _multilabel_confusion_matrix_compute(confmat, normalize)


def confusion_matrix(
    preds: Array,
    target: Array,
    task: str,
    threshold: float = 0.5,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    normalize: Optional[str] = None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: :591-646)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_confusion_matrix(preds, target, threshold, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        if not isinstance(num_classes, int):
            raise ValueError(f"`num_classes` is expected to be `int` but `{type(num_classes)} was passed.`")
        return multiclass_confusion_matrix(preds, target, num_classes, normalize, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        if not isinstance(num_labels, int):
            raise ValueError(f"`num_labels` is expected to be `int` but `{type(num_labels)} was passed.`")
        return multilabel_confusion_matrix(preds, target, num_labels, threshold, normalize, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
