"""Group-fairness functionals (reference: functional/classification/group_fairness.py).

TPU-first design: the reference sorts by group, splits on host (`_flexible_bincount
(...).cpu().tolist()` + ``torch.split``, group_fairness.py:51-81) and loops over the
groups. Here per-group tp/fp/tn/fn come from ONE fused bincount over the joint index
``group * 4 + 2*target + preds`` — a single XLA scatter-add, no host round-trip, static
``(num_groups, 4)`` output shape.
"""
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.stat_scores import (
    _binary_stat_scores_arg_validation,
    _binary_stat_scores_format,
    _binary_stat_scores_tensor_validation,
)
from metrics_tpu.utils.compute import _safe_divide


def _groups_validation(groups: Array, num_groups: int) -> None:
    """Validate group tensor (reference: group_fairness.py:29-43).

    Delta vs reference: ids ``>= num_groups`` and negative ids are rejected outright
    (the reference's ``> num_groups`` off-by-one lets an id equal to ``num_groups``
    through and emits a surprise extra group; the static-shape scatter kernel here
    would silently drop such samples instead, so they are made a hard error).
    """
    g = np.asarray(groups)
    if g.size and g.max() >= num_groups:
        raise ValueError(
            f"The largest number in the groups tensor is {g.max()}, which is larger than the specified"
            f" number of groups {num_groups}. The group identifiers should be ``0, 1, ..., (num_groups - 1)``."
        )
    if g.size and g.min() < 0:
        raise ValueError(
            f"The smallest number in the groups tensor is {g.min()}; negative group ids are not valid."
            " The group identifiers should be ``0, 1, ..., (num_groups - 1)``."
        )
    if not np.issubdtype(g.dtype, np.integer):
        raise ValueError(f"Expected dtype of argument groups to be int, not {g.dtype}.")


def _groups_format(groups: Array) -> Array:
    """Reshape groups to correspond to preds and target (reference: group_fairness.py:46-48)."""
    groups = jnp.asarray(groups)
    return groups.reshape(groups.shape[0], -1)


def _binary_groups_stat_scores_update(
    preds: Array, target: Array, groups: Array, num_groups: int
) -> Tuple[Array, Array, Array, Array]:
    """Per-group (tp, fp, tn, fn), each shape ``(num_groups,)``, via one fused
    scatter-add. Replaces the reference's host-side sort/split/loop
    (group_fairness.py:57-81)."""
    groups = groups.ravel()
    # out-of-range group ids get zero weight (jit-safe; validation rejects them eagerly)
    valid = (target.ravel() >= 0) & (groups >= 0) & (groups < num_groups)
    mapping = jnp.clip(groups, 0, num_groups - 1) * 4 + 2 * jnp.maximum(target, 0).ravel() + preds.ravel()
    weights = valid.astype(jnp.int32)
    bins = jnp.zeros(4 * num_groups, dtype=jnp.int32).at[mapping].add(weights)
    bins = bins.reshape(num_groups, 4)  # columns: t0p0=tn, t0p1=fp, t1p0=fn, t1p1=tp
    tn, fp, fn, tp = bins[:, 0], bins[:, 1], bins[:, 2], bins[:, 3]
    return tp, fp, tn, fn


def _binary_groups_stat_scores(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> List[Tuple[Array, Array, Array, Array]]:
    """Group stat scores as a per-group list (reference: group_fairness.py:51-81)."""
    if validate_args:
        _binary_stat_scores_arg_validation(threshold, "global", ignore_index)
        _binary_stat_scores_tensor_validation(preds, target, "global", ignore_index)
        _groups_validation(groups, num_groups)
    preds, target = _binary_stat_scores_format(preds, target, threshold, ignore_index)
    groups = _groups_format(groups)
    tp, fp, tn, fn = _binary_groups_stat_scores_update(preds, target, groups, num_groups)
    return [(tp[g], fp[g], tn[g], fn[g]) for g in range(num_groups)]


def _groups_reduce(group_stats: List[Tuple[Array, Array, Array, Array]]) -> Dict[str, Array]:
    """Rates per group (reference: group_fairness.py:84-89)."""
    return {
        f"group_{group}": jnp.stack(stats) / jnp.stack(stats).sum() for group, stats in enumerate(group_stats)
    }


def _groups_stat_transform(group_stats: List[Tuple[Array, Array, Array, Array]]) -> Dict[str, Array]:
    """Stack per-statistic tensors (reference: group_fairness.py:92-100)."""
    return {
        "tp": jnp.stack([stat[0] for stat in group_stats]),
        "fp": jnp.stack([stat[1] for stat in group_stats]),
        "tn": jnp.stack([stat[2] for stat in group_stats]),
        "fn": jnp.stack([stat[3] for stat in group_stats]),
    }


def binary_groups_stat_rates(
    preds: Array,
    target: Array,
    groups: Array,
    num_groups: int,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """True/false positive and negative rates per group (reference: group_fairness.py:103-160).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import binary_groups_stat_rates
        >>> target = jnp.array([0, 1, 0, 1, 0, 1])
        >>> preds = jnp.array([0, 1, 0, 1, 0, 1])
        >>> groups = jnp.array([0, 1, 0, 1, 0, 1])
        >>> binary_groups_stat_rates(preds, target, groups, 2)
        {'group_0': Array([0., 0., 1., 0.], dtype=float32), 'group_1': Array([1., 0., 0., 0.], dtype=float32)}
    """
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    return _groups_reduce(group_stats)


def _compute_binary_demographic_parity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    """Demographic parity from binary stats (reference: group_fairness.py:163-173)."""
    pop = tp + fp + tn + fn
    pos_rates = _safe_divide(tp + fp, pop)
    # groups with no samples (e.g. non-contiguous group ids) must not win the
    # argmin as phantom rate-0 groups (ADVICE r1)
    min_pos_rate_id = int(jnp.argmin(jnp.where(pop > 0, pos_rates, jnp.inf)))
    max_pos_rate_id = int(jnp.argmax(jnp.where(pop > 0, pos_rates, -jnp.inf)))
    ratio = _safe_divide(pos_rates[min_pos_rate_id], pos_rates[max_pos_rate_id])
    if int(jnp.sum(pop > 0)) < 2:
        # a single measurable group cannot be compared with anything: report NaN
        # instead of a perfect-fairness self-comparison
        ratio = jnp.asarray(jnp.nan, ratio.dtype)
    return {f"DP_{min_pos_rate_id}_{max_pos_rate_id}": ratio}


def demographic_parity(
    preds: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity ratio between groups (reference: group_fairness.py:176-236).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import demographic_parity
        >>> preds = jnp.array([0.11, 0.84, 0.22, 0.73, 0.33, 0.92])
        >>> groups = jnp.array([0, 1, 0, 1, 0, 1])
        >>> demographic_parity(preds, groups)
        {'DP_0_1': Array(0., dtype=float32)}
    """
    num_groups = int(np.asarray(groups).max()) + 1
    target = jnp.zeros_like(jnp.asarray(preds), dtype=jnp.int32).reshape(jnp.asarray(preds).shape)
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    transformed_group_stats = _groups_stat_transform(group_stats)
    return _compute_binary_demographic_parity(**transformed_group_stats)


def _compute_binary_equal_opportunity(tp: Array, fp: Array, tn: Array, fn: Array) -> Dict[str, Array]:
    """Equal opportunity from binary stats (reference: group_fairness.py:239-251)."""
    pop = tp + fn
    true_pos_rates = _safe_divide(tp, pop)
    # exclude zero-population groups from selection (ADVICE r1)
    min_pos_rate_id = int(jnp.argmin(jnp.where(pop > 0, true_pos_rates, jnp.inf)))
    max_pos_rate_id = int(jnp.argmax(jnp.where(pop > 0, true_pos_rates, -jnp.inf)))
    ratio = _safe_divide(true_pos_rates[min_pos_rate_id], true_pos_rates[max_pos_rate_id])
    if int(jnp.sum(pop > 0)) < 2:
        # fewer than two groups have positive targets: the comparison is undefined
        ratio = jnp.asarray(jnp.nan, ratio.dtype)
    return {f"EO_{min_pos_rate_id}_{max_pos_rate_id}": ratio}


def equal_opportunity(
    preds: Array,
    target: Array,
    groups: Array,
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Equal opportunity ratio between groups (reference: group_fairness.py:254-318).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.classification import equal_opportunity
        >>> target = jnp.array([0, 1, 1, 1, 0, 1])
        >>> preds = jnp.array([0.1, 0.9, 0.8, 0.4, 0.2, 0.7])
        >>> groups = jnp.array([0, 0, 0, 1, 1, 1])
        >>> equal_opportunity(preds, target, groups)
        {'EO_1_0': Array(0.5, dtype=float32)}
    """
    num_groups = int(np.asarray(groups).max()) + 1
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    transformed_group_stats = _groups_stat_transform(group_stats)
    return _compute_binary_equal_opportunity(**transformed_group_stats)


def binary_fairness(
    preds: Array,
    target: Array,
    groups: Array,
    task: str = "all",
    threshold: float = 0.5,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Dict[str, Array]:
    """Demographic parity and/or equal opportunity (reference: group_fairness.py:321-381)."""
    if task not in ["demographic_parity", "equal_opportunity", "all"]:
        raise ValueError(
            f"Expected argument `task` to either be ``demographic_parity``,"
            f"``equal_opportunity`` or ``all`` but got {task}."
        )
    if task == "demographic_parity":
        if target is not None:
            import warnings

            warnings.warn("The task demographic_parity does not require a target.", UserWarning)
        target = jnp.zeros(jnp.asarray(preds).shape, dtype=jnp.int32)

    num_groups = int(np.asarray(groups).max()) + 1
    group_stats = _binary_groups_stat_scores(preds, target, groups, num_groups, threshold, ignore_index, validate_args)
    transformed_group_stats = _groups_stat_transform(group_stats)

    if task == "demographic_parity":
        return _compute_binary_demographic_parity(**transformed_group_stats)
    if task == "equal_opportunity":
        return _compute_binary_equal_opportunity(**transformed_group_stats)

    results = {}
    results.update(_compute_binary_demographic_parity(**transformed_group_stats))
    results.update(_compute_binary_equal_opportunity(**transformed_group_stats))
    return results
