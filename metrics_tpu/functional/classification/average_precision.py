"""Average precision (AUPRC) functionals.

Capability parity with reference ``functional/classification/average_precision.py``
(_reduce_average_precision :43-67, binary :70-160, multiclass :163-279, multilabel
:282-400, dispatcher :403-460). Riemann sum over the shared PR-curve state.
"""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.auroc import _exact_mode_class_weights
from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import _is_confmat_state
from metrics_tpu.utils.compute import _safe_divide
from metrics_tpu.utils.enums import ClassificationTask
from metrics_tpu.utils.prints import rank_zero_warn


def _reduce_average_precision(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reference: average_precision.py:43-67."""
    if isinstance(precision, (jnp.ndarray, np.ndarray)) and not isinstance(precision, (list, tuple)):
        res = -jnp.sum((recall[:, 1:] - recall[:, :-1]) * precision[:, :-1], axis=1)
    else:
        res = jnp.stack([-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)])
    if average is None or average == "none":
        return res
    if bool(jnp.isnan(res).any()):
        rank_zero_warn(
            f"Average precision score for one or more classes was `nan`. Ignoring these classes in {average}-average",
            UserWarning,
        )
    idx = ~jnp.isnan(res)
    if average == "macro":
        return jnp.where(idx, res, 0.0).sum() / idx.sum()
    if average == "weighted" and weights is not None:
        weights = jnp.where(idx, weights, 0.0)
        weights = _safe_divide(weights, weights.sum())
        return jnp.where(idx, res * weights, 0.0).sum()
    raise ValueError("Received an incompatible combinations of inputs to make reduction.")


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
) -> Array:
    """Reference: average_precision.py:70-75."""
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds)
    return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Binary AP (reference: average_precision.py:78-160)."""
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_average_precision_compute(state, thresholds)


def _multiclass_average_precision_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None), but got {average}"
        )


def _multiclass_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    """Reference: average_precision.py:163-175."""
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    return _reduce_average_precision(
        precision,
        recall,
        average,
        weights=(
            _exact_mode_class_weights(state[1], num_classes)
            if thresholds is None
            else state[0][:, 1, :].sum(-1).astype(jnp.float32)
        ),
    )


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass AP (reference: average_precision.py:178-279)."""
    if validate_args:
        _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )


def _multilabel_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    """Reference: average_precision.py:282-310."""
    if average == "micro":
        if _is_confmat_state(state) and thresholds is not None:
            return _binary_average_precision_compute(state.sum(1), thresholds)
        preds = np.asarray(state[0]).ravel()
        target = np.asarray(state[1]).ravel()
        if ignore_index is not None:
            idx = target < 0
            preds = preds[~idx]
            target = target[~idx]
        return _binary_average_precision_compute((preds, target), thresholds)

    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    if thresholds is None:
        t = np.asarray(state[1])
        weights = jnp.asarray((t == 1).sum(0).astype(np.float32))
    else:
        weights = state[0][:, 1, :].sum(-1).astype(jnp.float32)
    return _reduce_average_precision(precision, recall, average, weights=weights)


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel AP (reference: average_precision.py:313-400)."""
    if validate_args:
        _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: average_precision.py:403-460)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
