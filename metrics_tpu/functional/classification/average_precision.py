"""Average precision (AUPRC) functionals.

Capability parity with reference ``functional/classification/average_precision.py``
(_reduce_average_precision :43-67, binary :70-160, multiclass :163-279, multilabel
:282-400, dispatcher :403-460). Riemann sum over the shared PR-curve state.
"""
from typing import List, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.auroc import _reduce_scores
from metrics_tpu.functional.classification.precision_recall_curve import (
    _binary_precision_recall_curve_arg_validation,
    _binary_precision_recall_curve_compute,
    _binary_precision_recall_curve_format,
    _binary_precision_recall_curve_tensor_validation,
    _binary_precision_recall_curve_update,
    _multiclass_precision_recall_curve_arg_validation,
    _multiclass_precision_recall_curve_compute,
    _multiclass_precision_recall_curve_format,
    _multiclass_precision_recall_curve_tensor_validation,
    _multiclass_precision_recall_curve_update,
    _multilabel_precision_recall_curve_arg_validation,
    _multilabel_precision_recall_curve_compute,
    _multilabel_precision_recall_curve_format,
    _multilabel_precision_recall_curve_tensor_validation,
    _multilabel_precision_recall_curve_update,
)
from metrics_tpu.functional.classification.roc import _is_confmat_state
from metrics_tpu.ops.clf_curve import (
    binary_average_precision_exact,
    multiclass_average_precision_exact,
    multilabel_average_precision_exact,
)
from metrics_tpu.utils.enums import ClassificationTask


def _reduce_average_precision(
    precision: Union[Array, List[Array]],
    recall: Union[Array, List[Array]],
    average: Optional[str] = "macro",
    weights: Optional[Array] = None,
) -> Array:
    """Reference: average_precision.py:43-67 (reduction shared with AUROC)."""
    if isinstance(precision, (jnp.ndarray, np.ndarray)) and not isinstance(precision, (list, tuple)):
        res = -jnp.sum((recall[:, 1:] - recall[:, :-1]) * precision[:, :-1], axis=1)
    else:
        res = jnp.stack([-jnp.sum((r[1:] - r[:-1]) * p[:-1]) for p, r in zip(precision, recall)])
    return _reduce_scores(res, average, weights)


def _binary_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    thresholds: Optional[Array],
    tolerance: float = 0.0,
    tolerance_bits: int = 12,
) -> Array:
    """Reference: average_precision.py:70-75. Exact mode runs fully on device
    (sort+cumsum kernel, ops/clf_curve.py); ``tolerance > 0`` opts into the
    certified sublinear sketch tier when the bracket width fits."""
    if not _is_confmat_state(state):
        return binary_average_precision_exact(
            state[0], state[1], tolerance=tolerance, tolerance_bits=tolerance_bits
        )
    precision, recall, _ = _binary_precision_recall_curve_compute(state, thresholds)
    return -jnp.sum((recall[1:] - recall[:-1]) * precision[:-1])


def binary_average_precision(
    preds: Array,
    target: Array,
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
    tolerance: float = 0.0,
    tolerance_bits: int = 12,
) -> Array:
    """Binary AP (reference: average_precision.py:78-160).

    ``tolerance > 0`` permits the sublinear sketch tier: when the certified
    bracket width at ``tolerance_bits`` fits, the bracket midpoint is served
    (no sort); otherwise the exact tier runs unchanged.
    """
    if validate_args:
        _binary_precision_recall_curve_arg_validation(thresholds, ignore_index)
        _binary_precision_recall_curve_tensor_validation(preds, target, ignore_index)
    preds, target, thresholds = _binary_precision_recall_curve_format(preds, target, thresholds, ignore_index)
    state = _binary_precision_recall_curve_update(preds, target, thresholds)
    return _binary_average_precision_compute(state, thresholds, tolerance=tolerance, tolerance_bits=tolerance_bits)


def _multiclass_average_precision_arg_validation(
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multiclass_precision_recall_curve_arg_validation(num_classes, thresholds, ignore_index)
    if average not in ("macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('macro', 'weighted', 'none', None), but got {average}"
        )


def _multiclass_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds: Optional[Array] = None,
) -> Array:
    """Reference: average_precision.py:163-175. Exact mode: vmapped OVR device kernel."""
    if thresholds is None:
        res, pos = multiclass_average_precision_exact(state[0], state[1])
        return _reduce_scores(res, average, weights=pos)
    precision, recall, _ = _multiclass_precision_recall_curve_compute(state, num_classes, thresholds)
    return _reduce_average_precision(
        precision, recall, average, weights=state[0][:, 1, :].sum(-1).astype(jnp.float32)
    )


def multiclass_average_precision(
    preds: Array,
    target: Array,
    num_classes: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multiclass AP (reference: average_precision.py:178-279)."""
    if validate_args:
        _multiclass_average_precision_arg_validation(num_classes, average, thresholds, ignore_index)
        _multiclass_precision_recall_curve_tensor_validation(preds, target, num_classes, ignore_index)
    preds, target, thresholds = _multiclass_precision_recall_curve_format(
        preds, target, num_classes, thresholds, ignore_index
    )
    state = _multiclass_precision_recall_curve_update(preds, target, num_classes, thresholds)
    return _multiclass_average_precision_compute(state, num_classes, average, thresholds)


def _multilabel_average_precision_arg_validation(
    num_labels: int,
    average: Optional[str],
    thresholds=None,
    ignore_index: Optional[int] = None,
) -> None:
    _multilabel_precision_recall_curve_arg_validation(num_labels, thresholds, ignore_index)
    if average not in ("micro", "macro", "weighted", "none", None):
        raise ValueError(
            f"Expected argument `average` to be one of ('micro', 'macro', 'weighted', 'none', None), but got {average}"
        )


def _multilabel_average_precision_compute(
    state: Union[Array, Tuple[Array, Array]],
    num_labels: int,
    average: Optional[str],
    thresholds: Optional[Array],
    ignore_index: Optional[int] = None,
) -> Array:
    """Reference: average_precision.py:282-310. Exact mode: vmapped per-label device
    kernel (negative targets excluded by the kernel's validity mask)."""
    if average == "micro":
        if _is_confmat_state(state) and thresholds is not None:
            return _binary_average_precision_compute(state.sum(1), thresholds)
        preds = jnp.asarray(state[0]).ravel()
        target = jnp.asarray(state[1]).ravel()
        return _binary_average_precision_compute((preds, target), thresholds)

    if thresholds is None:
        res, pos = multilabel_average_precision_exact(state[0], state[1])
        return _reduce_scores(res, average, weights=pos)
    precision, recall, _ = _multilabel_precision_recall_curve_compute(state, num_labels, thresholds, ignore_index)
    return _reduce_average_precision(
        precision, recall, average, weights=state[0][:, 1, :].sum(-1).astype(jnp.float32)
    )


def multilabel_average_precision(
    preds: Array,
    target: Array,
    num_labels: int,
    average: Optional[str] = "macro",
    thresholds=None,
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Multilabel AP (reference: average_precision.py:313-400)."""
    if validate_args:
        _multilabel_average_precision_arg_validation(num_labels, average, thresholds, ignore_index)
        _multilabel_precision_recall_curve_tensor_validation(preds, target, num_labels, ignore_index)
    preds, target, thresholds = _multilabel_precision_recall_curve_format(
        preds, target, num_labels, thresholds, ignore_index
    )
    state = _multilabel_precision_recall_curve_update(preds, target, num_labels, thresholds)
    return _multilabel_average_precision_compute(state, num_labels, average, thresholds, ignore_index)


def average_precision(
    preds: Array,
    target: Array,
    task: str,
    thresholds=None,
    num_classes: Optional[int] = None,
    num_labels: Optional[int] = None,
    average: Optional[str] = "macro",
    ignore_index: Optional[int] = None,
    validate_args: bool = True,
) -> Array:
    """Task dispatcher (reference: average_precision.py:403-460)."""
    task = ClassificationTask.from_str(task)
    if task == ClassificationTask.BINARY:
        return binary_average_precision(preds, target, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTICLASS:
        assert isinstance(num_classes, int)
        return multiclass_average_precision(preds, target, num_classes, average, thresholds, ignore_index, validate_args)
    if task == ClassificationTask.MULTILABEL:
        assert isinstance(num_labels, int)
        return multilabel_average_precision(preds, target, num_labels, average, thresholds, ignore_index, validate_args)
    raise ValueError(f"Not handled value: {task}")
