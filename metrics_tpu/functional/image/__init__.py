from metrics_tpu.functional.image.d_lambda import spectral_distortion_index
from metrics_tpu.functional.image.ergas import error_relative_global_dimensionless_synthesis
from metrics_tpu.functional.image.gradients import image_gradients
from metrics_tpu.functional.image.lpips import learned_perceptual_image_patch_similarity
from metrics_tpu.functional.image.psnr import peak_signal_noise_ratio
from metrics_tpu.functional.image.psnrb import peak_signal_noise_ratio_with_blocked_effect
from metrics_tpu.functional.image.rase import relative_average_spectral_error
from metrics_tpu.functional.image.rmse_sw import root_mean_squared_error_using_sliding_window
from metrics_tpu.functional.image.sam import spectral_angle_mapper
from metrics_tpu.functional.image.ssim import (
    multiscale_structural_similarity_index_measure,
    structural_similarity_index_measure,
)
from metrics_tpu.functional.image.tv import total_variation
from metrics_tpu.functional.image.uqi import universal_image_quality_index

__all__ = [
    "error_relative_global_dimensionless_synthesis",
    "image_gradients",
    "multiscale_structural_similarity_index_measure",
    "learned_perceptual_image_patch_similarity",
    "peak_signal_noise_ratio",
    "peak_signal_noise_ratio_with_blocked_effect",
    "relative_average_spectral_error",
    "root_mean_squared_error_using_sliding_window",
    "spectral_angle_mapper",
    "spectral_distortion_index",
    "structural_similarity_index_measure",
    "total_variation",
    "universal_image_quality_index",
]
