"""Root-functional deprecation shims (reference: functional/image/_deprecated.py).

``metrics_tpu.functional.<name>`` warns; ``metrics_tpu.functional.image.<name>``
stays silent (reference utilities/prints.py:67-72).
"""
from metrics_tpu.functional.image import error_relative_global_dimensionless_synthesis, image_gradients, multiscale_structural_similarity_index_measure, peak_signal_noise_ratio, relative_average_spectral_error, root_mean_squared_error_using_sliding_window, spectral_angle_mapper, spectral_distortion_index, structural_similarity_index_measure, total_variation, universal_image_quality_index
from metrics_tpu.utils.prints import _root_func_shim

_error_relative_global_dimensionless_synthesis = _root_func_shim(error_relative_global_dimensionless_synthesis, "error_relative_global_dimensionless_synthesis", "image")
_image_gradients = _root_func_shim(image_gradients, "image_gradients", "image")
_multiscale_structural_similarity_index_measure = _root_func_shim(multiscale_structural_similarity_index_measure, "multiscale_structural_similarity_index_measure", "image")
_peak_signal_noise_ratio = _root_func_shim(peak_signal_noise_ratio, "peak_signal_noise_ratio", "image")
_relative_average_spectral_error = _root_func_shim(relative_average_spectral_error, "relative_average_spectral_error", "image")
_root_mean_squared_error_using_sliding_window = _root_func_shim(root_mean_squared_error_using_sliding_window, "root_mean_squared_error_using_sliding_window", "image")
_spectral_angle_mapper = _root_func_shim(spectral_angle_mapper, "spectral_angle_mapper", "image")
_spectral_distortion_index = _root_func_shim(spectral_distortion_index, "spectral_distortion_index", "image")
_structural_similarity_index_measure = _root_func_shim(structural_similarity_index_measure, "structural_similarity_index_measure", "image")
_total_variation = _root_func_shim(total_variation, "total_variation", "image")
_universal_image_quality_index = _root_func_shim(universal_image_quality_index, "universal_image_quality_index", "image")

__all__ = ["_error_relative_global_dimensionless_synthesis", "_image_gradients", "_multiscale_structural_similarity_index_measure", "_peak_signal_noise_ratio", "_relative_average_spectral_error", "_root_mean_squared_error_using_sliding_window", "_spectral_angle_mapper", "_spectral_distortion_index", "_structural_similarity_index_measure", "_total_variation", "_universal_image_quality_index"]
