"""Sliding-window RMSE functional (reference: functional/image/rmse_sw.py:22-130)."""
from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.helper import _uniform_filter
from metrics_tpu.utils.checks import _check_same_shape


def _rmse_sw_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_val_sum: Optional[Array],
    rmse_map: Optional[Array],
    total_images: Optional[Array],
) -> Tuple[Array, Array, Array]:
    """Accumulate per-window RMSE (reference: :26-85)."""
    _check_same_shape(preds, target)
    if len(preds.shape) != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. But got {preds.shape}.")
    if round(window_size / 2) >= target.shape[2] or round(window_size / 2) >= target.shape[3]:
        raise ValueError(
            f"Parameter `round(window_size / 2)` is expected to be smaller than"
            f" {min(target.shape[2], target.shape[3])} but got {round(window_size / 2)}."
        )
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)

    total_images = (total_images if total_images is not None else 0) + target.shape[0]
    error = (target - preds) ** 2
    error = _uniform_filter(error, window_size)
    _rmse_map = jnp.sqrt(error)
    crop_slide = round(window_size / 2)

    val = _rmse_map[:, :, crop_slide:-crop_slide, crop_slide:-crop_slide].sum(0).mean()
    rmse_val_sum = (rmse_val_sum + val) if rmse_val_sum is not None else val
    rmse_map = (rmse_map + _rmse_map.sum(0)) if rmse_map is not None else _rmse_map.sum(0)
    return rmse_val_sum, rmse_map, jnp.asarray(total_images)


def _rmse_sw_compute(
    rmse_val_sum: Optional[Array], rmse_map: Array, total_images: Array
) -> Tuple[Optional[Array], Array]:
    rmse = rmse_val_sum / total_images if rmse_val_sum is not None else None
    rmse_map = rmse_map / total_images
    return rmse, rmse_map


def root_mean_squared_error_using_sliding_window(
    preds: Array, target: Array, window_size: int = 8, return_rmse_map: bool = False
) -> Union[Optional[Array], Tuple[Optional[Array], Array]]:
    """RMSE over sliding windows (reference: :107-130)."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError("Argument `window_size` is expected to be a positive integer.")
    rmse_val_sum, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=None, total_images=None
    )
    rmse, rmse_map = _rmse_sw_compute(rmse_val_sum, rmse_map, total_images)
    if return_rmse_map:
        return rmse, rmse_map
    return rmse
