"""ERGAS functional (reference: functional/image/ergas.py:22-100)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _ergas_update(preds: Array, target: Array):
    _check_same_shape(preds, target)
    if len(preds.shape) != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    return jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)


def _ergas_compute(preds: Array, target: Array, ratio: float = 4, reduction: Optional[str] = "elementwise_mean") -> Array:
    b, c, h, w = preds.shape
    preds = preds.reshape(b, c, h * w)
    target = target.reshape(b, c, h * w)

    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=2)
    rmse_per_band = jnp.sqrt(sum_squared_error / (h * w))
    mean_target = jnp.mean(target, axis=2)

    ergas_score = 100 * ratio * jnp.sqrt(jnp.sum((rmse_per_band / mean_target) ** 2, axis=1) / c)
    return reduce(ergas_score, reduction)


def error_relative_global_dimensionless_synthesis(
    preds: Array, target: Array, ratio: float = 4, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """ERGAS."""
    preds, target = _ergas_update(preds, target)
    return _ergas_compute(preds, target, ratio, reduction)
