"""LPIPS functional (reference: functional/image/lpips.py / image/lpip.py:42).

See :mod:`metrics_tpu.models.lpips` for the network port and weight loading.
"""
from typing import Any, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.models.lpips import load_lpips, lpips_forward


def _lpips_valid_img(img: Array, normalize: bool) -> bool:
    """Shape/value check mirroring reference ``_valid_img``."""
    value_check = bool(img.max() <= 1.0 and img.min() >= 0.0) if normalize else True
    return img.ndim == 4 and img.shape[1] == 3 and value_check


def learned_perceptual_image_patch_similarity(
    img1: Array,
    img2: Array,
    net_type: str = "alex",
    reduction: str = "mean",
    normalize: bool = False,
    backbone_weights: Optional[str] = None,
    linear_weights: Optional[str] = None,
) -> Array:
    """LPIPS perceptual distance between two NCHW RGB batches (lower = more similar).

    Args:
        img1 / img2: image batches, in [-1, 1] (or [0, 1] with ``normalize=True``).
        net_type: ``"vgg"`` | ``"alex"`` | ``"squeeze"`` backbone.
        reduction: ``"mean"`` or ``"sum"`` over the batch.
        normalize: inputs are in [0, 1].
        backbone_weights / linear_weights: local weight files (see models.lpips).
    """
    if not (_lpips_valid_img(img1, normalize) and _lpips_valid_img(img2, normalize)):
        raise ValueError(
            "Expected both input arguments to be normalized tensors with shape [N, 3, H, W]."
            f" Got input with shape {img1.shape} and {img2.shape} and values in range"
            f" {[img1.min(), img1.max()]} and {[img2.min(), img2.max()]} when all values are"
            f" expected to be in the {[0, 1] if normalize else [-1, 1]} range."
        )
    backbone, lins = load_lpips(net_type, backbone_weights, linear_weights)
    loss = lpips_forward(backbone, lins, img1, img2, net_type, normalize)
    return loss.mean() if reduction == "mean" else loss.sum()
