"""RASE functional (reference: functional/image/rase.py:20-100)."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.helper import _uniform_filter
from metrics_tpu.functional.image.rmse_sw import _rmse_sw_compute, _rmse_sw_update


def _rase_update(
    preds: Array,
    target: Array,
    window_size: int,
    rmse_map: Optional[Array],
    target_sum: Optional[Array],
    total_images: Optional[Array],
) -> Tuple[Array, Array, Array]:
    """Reference: :24-45 (the /window_size**2 rescale of the already-averaged uniform
    filter mirrors the reference exactly)."""
    _, rmse_map, total_images = _rmse_sw_update(
        preds, target, window_size, rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images
    )
    target = jnp.asarray(target, jnp.float32)
    inc = jnp.sum(_uniform_filter(target, window_size) / (window_size**2), axis=0)
    target_sum = target_sum + inc if target_sum is not None else inc
    return rmse_map, target_sum, total_images


def _rase_compute(rmse_map: Array, target_sum: Array, total_images: Array, window_size: int) -> Array:
    """Reference: :48-66."""
    _, rmse_map = _rmse_sw_compute(rmse_val_sum=None, rmse_map=rmse_map, total_images=total_images)
    target_mean = target_sum / total_images
    target_mean = target_mean.mean(0)  # mean over channels
    rase_map = 100 / target_mean * jnp.sqrt(jnp.mean(rmse_map**2, axis=0))
    crop_slide = round(window_size / 2)
    return jnp.mean(rase_map[crop_slide:-crop_slide, crop_slide:-crop_slide])


def relative_average_spectral_error(preds: Array, target: Array, window_size: int = 8) -> Array:
    """RASE (reference: :69-100)."""
    if not isinstance(window_size, int) or window_size < 1:
        raise ValueError(f"Argument `window_size` is expected to be a positive integer, but got {window_size}")
    rmse_map, target_sum, total_images = _rase_update(
        preds, target, window_size, rmse_map=None, target_sum=None, total_images=None
    )
    return _rase_compute(rmse_map, target_sum, total_images, window_size)
