"""Universal Image Quality Index (reference: functional/image/uqi.py:30-140).

UQI = SSIM without the stabilization constants (c1 = c2 = 0).
"""
from typing import Optional, Sequence

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.helper import _gaussian, _reflection_pad_2d, _separable_blur_2d
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def universal_image_quality_index(
    preds: Array,
    target: Array,
    kernel_size: Sequence[int] = (11, 11),
    sigma: Sequence[float] = (1.5, 1.5),
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    """UQI.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> preds = jax.random.uniform(jax.random.PRNGKey(0), (1, 1, 16, 16))
        >>> target = preds * 0.75
        >>> from metrics_tpu.functional.image import universal_image_quality_index
        >>> bool(universal_image_quality_index(preds, target) > 0.9)
        True
    """
    _check_same_shape(preds, target)
    if len(preds.shape) != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if not isinstance(kernel_size, Sequence) or len(kernel_size) != 2:
        raise ValueError(f"Expected `kernel_size` to be a sequence of length 2. Got {kernel_size}.")
    if any(x % 2 == 0 or x <= 0 for x in kernel_size):
        raise ValueError(f"Expected `kernel_size` to have odd positive number. Got {kernel_size}.")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)

    g_h = _gaussian(kernel_size[0], sigma[0])[0]
    g_w = _gaussian(kernel_size[1], sigma[1])[0]
    pad_h = (kernel_size[0] - 1) // 2
    pad_w = (kernel_size[1] - 1) // 2

    preds = _reflection_pad_2d(preds, pad_h, pad_w)
    target = _reflection_pad_2d(target, pad_h, pad_w)

    input_list = jnp.concatenate((preds, target, preds * preds, target * target, preds * target))
    outputs = _separable_blur_2d(input_list, g_h, g_w)
    b = preds.shape[0]
    output_list = [outputs[i * b : (i + 1) * b] for i in range(5)]

    mu_pred_sq = output_list[0] ** 2
    mu_target_sq = output_list[1] ** 2
    mu_pred_target = output_list[0] * output_list[1]

    sigma_pred_sq = output_list[2] - mu_pred_sq
    sigma_target_sq = output_list[3] - mu_target_sq
    sigma_pred_target = output_list[4] - mu_pred_target

    upper = 2 * sigma_pred_target
    lower = sigma_pred_sq + sigma_target_sq
    eps = jnp.finfo(jnp.float32).eps
    uqi_idx = ((2 * mu_pred_target) * upper) / ((mu_pred_sq + mu_target_sq) * lower + eps)
    uqi_idx = uqi_idx[..., pad_h:-pad_h, pad_w:-pad_w]
    return reduce(uqi_idx, reduction)
