"""Spectral angle mapper functional (reference: functional/image/sam.py:22-110)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _sam_update(preds: Array, target: Array):
    _check_same_shape(preds, target)
    if len(preds.shape) != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if preds.shape[1] <= 1:
        raise ValueError(f"Expected channel dimension of `preds` and `target` to be larger than 1. Got {preds.shape[1]}.")
    return jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)


def _sam_compute(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    dot_product = (preds * target).sum(axis=1)
    preds_norm = jnp.linalg.norm(preds, axis=1)
    target_norm = jnp.linalg.norm(target, axis=1)
    sam_score = jnp.arccos(jnp.clip(dot_product / (preds_norm * target_norm), -1.0, 1.0))
    return reduce(sam_score, reduction)


def spectral_angle_mapper(preds: Array, target: Array, reduction: Optional[str] = "elementwise_mean") -> Array:
    """Spectral angle mapper (radians)."""
    preds, target = _sam_update(preds, target)
    return _sam_compute(preds, target, reduction)
