"""Image gradients functional (reference: functional/image/gradients.py)."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array


def image_gradients(img: Array) -> Tuple[Array, Array]:
    """(dy, dx) first differences, zero-padded at the far edge.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.image import image_gradients
        >>> img = jnp.arange(16.0).reshape(1, 1, 4, 4)
        >>> dy, dx = image_gradients(img)
        >>> dy[0, 0, :, :]
        Array([[4., 4., 4., 4.],
               [4., 4., 4., 4.],
               [4., 4., 4., 4.],
               [0., 0., 0., 0.]], dtype=float32)
    """
    if img.ndim != 4:
        raise RuntimeError(f"The size of the image tensor {img.shape} is not 4-dimensional")
    img = jnp.asarray(img, jnp.float32)
    dy = img[..., 1:, :] - img[..., :-1, :]
    dx = img[..., :, 1:] - img[..., :, :-1]
    dy = jnp.pad(dy, ((0, 0), (0, 0), (0, 1), (0, 0)))
    dx = jnp.pad(dx, ((0, 0), (0, 0), (0, 0), (0, 1)))
    return dy, dx
