"""Spectral distortion index functional (reference: functional/image/d_lambda.py:22-100)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.image.uqi import universal_image_quality_index
from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def spectral_distortion_index(
    preds: Array, target: Array, p: int = 1, reduction: Optional[str] = "elementwise_mean"
) -> Array:
    """D_lambda: spectral distortion between fused and low-res multispectral bands."""
    _check_same_shape(preds, target)
    if len(preds.shape) != 4:
        raise ValueError(f"Expected `preds` and `target` to have BxCxHxW shape. Got preds: {preds.shape}.")
    if not isinstance(p, int) or p <= 0:
        raise ValueError(f"Expected `p` to be a positive integer. Got p: {p}.")
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    length = preds.shape[1]

    m1 = jnp.zeros((length, length))
    m2 = jnp.zeros((length, length))
    for k in range(length):
        for r in range(k, length):
            q1 = universal_image_quality_index(target[:, k : k + 1], target[:, r : r + 1])
            q2 = universal_image_quality_index(preds[:, k : k + 1], preds[:, r : r + 1])
            m1 = m1.at[k, r].set(q1)
            m2 = m2.at[k, r].set(q2)
            m1 = m1.at[r, k].set(q1)
            m2 = m2.at[r, k].set(q2)

    diff = jnp.abs(m1 - m2) ** p
    # only off-diagonal terms
    mask = 1.0 - jnp.eye(length)
    output = (diff * mask).sum() / (length * (length - 1))
    return output ** (1.0 / p)
