"""Total variation functional (reference: functional/image/tv.py:20-70)."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array


def _total_variation_update(img: Array) -> Tuple[Array, int]:
    if img.ndim != 4:
        raise RuntimeError(f"Expected input `img` to be an 4D tensor, but got {img.shape}")
    diff1 = img[..., 1:, :] - img[..., :-1, :]
    diff2 = img[..., :, 1:] - img[..., :, :-1]
    res1 = jnp.abs(diff1).sum((1, 2, 3))
    res2 = jnp.abs(diff2).sum((1, 2, 3))
    return res1 + res2, img.shape[0]


def _total_variation_compute(score: Array, num_elements: int, reduction: Optional[str]) -> Array:
    if reduction == "mean":
        return score.sum() / num_elements
    if reduction == "sum":
        return score.sum()
    if reduction is None or reduction == "none":
        return score
    raise ValueError("Expected argument `reduction` to either be 'sum', 'mean', 'none' or None")


def total_variation(img: Array, reduction: Optional[str] = "sum") -> Array:
    """Total variation of an image batch.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.image import total_variation
        >>> img = jnp.arange(16.0).reshape(1, 1, 4, 4)
        >>> total_variation(img)
        Array(60., dtype=float32)
    """
    score, num_elements = _total_variation_update(jnp.asarray(img, jnp.float32))
    return _total_variation_compute(score, num_elements, reduction)
