"""Frechet distance math.

Reference ``image/fid.py:160-179`` computes ``tr(sqrtm(S1 @ S2))`` with scipy-style
eigvals of the (non-symmetric) product. TPU-first redesign (SURVEY.md SS7 hard part c):
use the symmetric form tr(sqrtm(S1)^T S2 sqrtm(S1)) via two Hermitian ``eigh``
factorizations — numerically stable on accelerator linear algebra and differentiable.
Run under ``jax_enable_x64`` for float64 parity with the reference (it requires f64,
image/fid.py:201-203); in f32 expect ~1e-4 relative drift on ill-conditioned covs.
"""
import jax.numpy as jnp
from jax import Array


def _sqrtm_psd(mat: Array) -> Array:
    """Matrix square root of a symmetric PSD matrix via eigh (clamped eigenvalues)."""
    vals, vecs = jnp.linalg.eigh(mat)
    vals = jnp.clip(vals, 0.0, None)
    return (vecs * jnp.sqrt(vals)) @ vecs.T


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array) -> Array:
    """Frechet distance between two multivariate normals (reference: image/fid.py:160-179)."""
    diff = mu1 - mu2
    s1_half = _sqrtm_psd(sigma1)
    inner = s1_half @ sigma2 @ s1_half
    vals = jnp.linalg.eigvalsh(inner)
    tr_covmean = jnp.sqrt(jnp.clip(vals, 0.0, None)).sum()
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean


def _mean_cov_from_sums(feat_sum: Array, feat_cov_sum: Array, n: Array):
    """(sum x, sum x x^T, n) -> (mean, unbiased covariance); reference image/fid.py:341-353."""
    mean = (feat_sum / n)[None, :]
    cov_num = feat_cov_sum - n * mean.T @ mean
    cov = cov_num / (n - 1)
    return mean.squeeze(0), cov
