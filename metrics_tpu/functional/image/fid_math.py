"""Frechet distance math.

Reference ``image/fid.py:160-179`` computes ``tr(sqrtm(S1 @ S2))`` with scipy-style
eigvals of the (non-symmetric) product in float64. TPU-first redesign (SURVEY.md SS7
hard part c): the trace of the matrix square root comes from a residual-guarded
coupled Newton-Schulz iteration — matmul-only, so it lives on the MXU and compiles in
~1s where TPU ``eigh``'s QR loops took 88s to compile and 0.4s to run at 2048
features. Accuracy (measured on 2048-d anisotropic covariances vs float64 scipy):
f32 Newton-Schulz best-iterate ~3e-6 relative FID error, vs ~2e-3 for the
symmetrized f32 eigh it replaces. Over-iterating NS diverges in f32, so the
iteration carries the lowest-residual iterate rather than the last one.
"""
import jax
import jax.numpy as jnp
from jax import Array


def _sqrtm_trace_newton_schulz(a: Array, iters: int = 25) -> Array:
    """trace(sqrtm(a)) for a matrix with nonnegative real spectrum (e.g. S1 @ S2).

    Coupled Newton-Schulz: with ``y0 = a/||a||``, iterate
    ``t = (3I - z y)/2; y <- y t; z <- t z`` so that y -> sqrtm(y0), z -> y0^-1/2.
    Each step costs 3 matmuls plus one for the residual ``||y y - y0||`` that
    selects the best iterate (quadratic convergence first, f32 rounding divergence
    later — NaNs compare False and therefore never replace the best).
    """
    norm = jnp.linalg.norm(a)
    scale = jnp.where(norm > 0, norm, 1.0)
    y0 = a / scale
    eye = jnp.eye(a.shape[0], dtype=a.dtype)

    def body(carry, _):
        y, z, best_tr, best_err = carry
        t = 0.5 * (3.0 * eye - z @ y)
        y_next = y @ t
        z_next = t @ z
        err = jnp.linalg.norm(y_next @ y_next - y0)
        better = err < best_err
        best_tr = jnp.where(better, jnp.trace(y_next), best_tr)
        best_err = jnp.where(better, err, best_err)
        return (y_next, z_next, best_tr, best_err), None

    init_err = jnp.linalg.norm(y0 @ y0 - y0)
    init = (y0, eye, jnp.trace(y0), init_err)
    (_, _, best_tr, _), _ = jax.lax.scan(body, init, None, length=iters)
    return best_tr * jnp.sqrt(scale)


def _sqrtm_trace_eigh(sigma1: Array, sigma2: Array, xp=jnp) -> Array:
    """tr(sqrtm(S1 S2)) via the symmetrized form tr(sqrtm(sqrtm(S1) S2 sqrtm(S1)))
    — two Hermitian eigendecompositions. More accurate than f32 Newton-Schulz on
    near-singular covariances (~3e-5 vs ~2e-3 relative) but TPU eigh QR loops cost
    ~88s of XLA compile time at 2048 features. ``xp`` selects the array namespace:
    the eager FID compute path calls this with numpy on float64 host arrays."""
    vals, vecs = xp.linalg.eigh(sigma1)
    vals = xp.clip(vals, 0.0, None)
    s1_half = (vecs * xp.sqrt(vals)) @ vecs.T
    inner = s1_half @ sigma2 @ s1_half
    return xp.sqrt(xp.clip(xp.linalg.eigvalsh(inner), 0.0, None)).sum()


def _compute_fid(mu1: Array, sigma1: Array, mu2: Array, sigma2: Array, method: str = "auto") -> Array:
    """Frechet distance between two multivariate normals (reference: image/fid.py:160-179).

    method: 'newton_schulz' (matmul-only, MXU-friendly, seconds to compile),
    'eigh' (symmetrized eigendecomposition, best f32 accuracy on near-singular
    covariances, pathological compile time on TPU), or 'auto' — Newton-Schulz on
    TPU, eigh elsewhere.
    """
    if method == "auto":
        method = "newton_schulz" if jax.default_backend() == "tpu" else "eigh"
    diff = mu1 - mu2
    if method == "newton_schulz":
        tr_covmean = _sqrtm_trace_newton_schulz(sigma1 @ sigma2)
    elif method == "eigh":
        tr_covmean = _sqrtm_trace_eigh(sigma1, sigma2)
    else:
        raise ValueError(f"Unknown FID sqrtm method: {method}")
    return diff @ diff + jnp.trace(sigma1) + jnp.trace(sigma2) - 2 * tr_covmean
