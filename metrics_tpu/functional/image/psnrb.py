"""PSNR with Blocked Effect functional (reference: functional/image/psnrb.py:21-130).

Pure-jnp with static index sets: the block-boundary / non-boundary column and row
index vectors depend only on the (static) image shape and block size, so the whole
update jits; the blocking-effect gate ``t`` is a branchless ``where``.
"""
import math
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array


def _compute_bef(x: Array, block_size: int = 8) -> Array:
    """Blocking effect factor of a grayscale NCHW batch (summed over the batch)."""
    _, channels, height, width = x.shape
    if channels > 1:
        raise ValueError(f"`psnrb` metric expects grayscale images, but got images with {channels} channels.")

    h_b = np.arange(block_size - 1, width - 1, block_size)
    h_bc = np.setdiff1d(np.arange(width - 1), h_b)
    v_b = np.arange(block_size - 1, height - 1, block_size)
    v_bc = np.setdiff1d(np.arange(height - 1), v_b)

    d_b = jnp.sum((x[:, :, :, h_b] - x[:, :, :, h_b + 1]) ** 2)
    d_bc = jnp.sum((x[:, :, :, h_bc] - x[:, :, :, h_bc + 1]) ** 2)
    d_b += jnp.sum((x[:, :, v_b, :] - x[:, :, v_b + 1, :]) ** 2)
    d_bc += jnp.sum((x[:, :, v_bc, :] - x[:, :, v_bc + 1, :]) ** 2)

    n_hb = height * (width / block_size) - 1
    n_hbc = (height * (width - 1)) - n_hb
    n_vb = width * (height / block_size) - 1
    n_vbc = (width * (height - 1)) - n_vb
    d_b = d_b / (n_hb + n_vb)
    d_bc = d_bc / (n_hbc + n_vbc)
    t = math.log2(block_size) / math.log2(min(height, width))
    return jnp.where(d_b > d_bc, t * (d_b - d_bc), 0.0)


def _psnrb_update(preds: Array, target: Array, block_size: int = 8) -> Tuple[Array, Array, Array]:
    sum_squared_error = jnp.sum((preds - target) ** 2)
    n_obs = jnp.asarray(target.size)
    bef = _compute_bef(preds, block_size=block_size)
    return sum_squared_error, bef, n_obs


def _psnrb_compute(sum_squared_error: Array, bef: Array, n_obs: Array, data_range: Array) -> Array:
    mse = sum_squared_error / n_obs + bef
    peak = jnp.where(data_range > 2, data_range.astype(jnp.float32) ** 2, 1.0)
    return 10 * jnp.log10(peak / mse)


def peak_signal_noise_ratio_with_blocked_effect(preds: Array, target: Array, block_size: int = 8) -> Array:
    """PSNR penalized by the blocking-effect factor (grayscale NCHW input).

    Example:
        >>> import jax
        >>> preds = jax.random.uniform(jax.random.PRNGKey(42), (1, 1, 28, 28))
        >>> target = jax.random.uniform(jax.random.PRNGKey(43), (1, 1, 28, 28))
        >>> float(peak_signal_noise_ratio_with_blocked_effect(preds, target)) > 0
        True
    """
    sum_squared_error, bef, n_obs = _psnrb_update(preds, target, block_size=block_size)
    data_range = target.max() - target.min()
    return _psnrb_compute(sum_squared_error, bef, n_obs, data_range)
