"""PSNR functional (reference: functional/image/psnr.py:20-140)."""
from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.distributed import reduce


def _psnr_compute(
    sum_squared_error: Array,
    n_obs: Array,
    data_range: Array,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
) -> Array:
    psnr_base_e = 2 * jnp.log(data_range) - jnp.log(sum_squared_error / n_obs)
    psnr_vals = psnr_base_e * (10 / jnp.log(jnp.asarray(base)))
    return reduce(psnr_vals, reduction=reduction)


def _psnr_update(preds: Array, target: Array, dim=None) -> Tuple[Array, Array]:
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    if dim is None:
        sum_squared_error = jnp.sum((preds - target) ** 2)
        return sum_squared_error, jnp.asarray(target.size)
    diff = preds - target
    sum_squared_error = jnp.sum(diff * diff, axis=dim)
    dim_list = [dim] if isinstance(dim, int) else list(dim)
    n_obs = 1
    for d in dim_list:
        n_obs *= target.shape[d]
    return sum_squared_error, jnp.asarray(n_obs)


def peak_signal_noise_ratio(
    preds: Array,
    target: Array,
    data_range: Optional[Union[float, Tuple[float, float]]] = None,
    base: float = 10.0,
    reduction: Optional[str] = "elementwise_mean",
    dim: Optional[Union[int, Tuple[int, ...]]] = None,
) -> Array:
    """PSNR.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.image import peak_signal_noise_ratio
        >>> pred = jnp.array([[0.0, 1.0], [2.0, 3.0]])
        >>> target = jnp.array([[3.0, 2.0], [1.0, 0.0]])
        >>> peak_signal_noise_ratio(pred, target)
        Array(2.552725, dtype=float32)
    """
    if dim is None and reduction != "elementwise_mean":
        from metrics_tpu.utils.prints import rank_zero_warn

        rank_zero_warn(f"The `reduction={reduction}` will not have any effect when `dim` is None.")
    _check_same_shape(preds, target)
    if data_range is None:
        if dim is not None:
            raise ValueError("The `data_range` must be given when `dim` is not None.")
        data_range = jnp.asarray(target.max() - target.min(), jnp.float32)
    elif isinstance(data_range, tuple):
        preds = jnp.clip(preds, data_range[0], data_range[1])
        target = jnp.clip(target, data_range[0], data_range[1])
        data_range = jnp.asarray(data_range[1] - data_range[0], jnp.float32)
    else:
        data_range = jnp.asarray(float(data_range))
    sum_squared_error, n_obs = _psnr_update(preds, target, dim=dim)
    return _psnr_compute(sum_squared_error, n_obs, data_range, base=base, reduction=reduction)
