"""Image kernel helpers.

Capability parity with reference ``functional/image/helper.py`` (gaussian/uniform
kernels) re-expressed on ``lax.conv_general_dilated``: depthwise (grouped) convs use
``feature_group_count`` and lower straight onto the TPU convolution units.
"""
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1-D gaussian window, normalized (reference: helper.py:11)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]  # (1, kernel_size)


def _gaussian_kernel_2d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """(C, 1, kh, kw) depthwise gaussian kernel (reference: helper.py:29)."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kx.T @ ky  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """(C, 1, kd, kh, kw) depthwise gaussian kernel (reference: helper.py:~80)."""
    k2d = _gaussian_kernel_2d(channel, kernel_size[:2], sigma[:2], dtype)[0, 0]
    kz = _gaussian(kernel_size[2], sigma[2], dtype)[0]
    kernel = k2d[:, :, None] * kz[None, None, :]
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _depthwise_conv2d(x: Array, kernel: Array) -> Array:
    """Grouped (per-channel) VALID conv: x (N,C,H,W), kernel (C,1,kh,kw)."""
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        feature_group_count=x.shape[1],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _depthwise_conv3d(x: Array, kernel: Array) -> Array:
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1, 1),
        padding="VALID",
        feature_group_count=x.shape[1],
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )


def _reflection_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _reflection_pad_3d(x: Array, pad_d: int, pad_w: int, pad_h: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w), (pad_d, pad_d)), mode="reflect")


def _avg_pool(x: Array, window: Tuple[int, ...]) -> Array:
    """Average pooling with stride == window (reference uses F.avg_pool2d/3d)."""
    nd = len(window)
    dims = (1, 1) + window
    out = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, dims, "VALID")
    return out / jnp.prod(jnp.asarray(window))


def _uniform_filter(x: Array, window_size: int) -> Array:
    """Same-size depthwise mean filter with symmetric padding (reference: helper.py:110,
    whose custom pad takes the first/last rows reversed = numpy 'symmetric')."""
    left = window_size // 2
    right = window_size - 1 - left
    x = jnp.pad(x, ((0, 0), (0, 0), (left, right), (left, right)), mode="symmetric")
    c = x.shape[1]
    kernel = jnp.ones((c, 1, window_size, window_size), x.dtype) / (window_size**2)
    return _depthwise_conv2d(x, kernel)
