"""Image kernel helpers.

Capability parity with reference ``functional/image/helper.py`` (gaussian/uniform
kernels) re-expressed on ``lax.conv_general_dilated``: depthwise (grouped) convs use
``feature_group_count`` and lower straight onto the TPU convolution units.
"""
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _gaussian(kernel_size: int, sigma: float, dtype=jnp.float32) -> Array:
    """1-D gaussian window, normalized (reference: helper.py:11)."""
    dist = jnp.arange((1 - kernel_size) / 2, (1 + kernel_size) / 2, 1.0, dtype=dtype)
    gauss = jnp.exp(-((dist / sigma) ** 2) / 2)
    return (gauss / gauss.sum())[None, :]  # (1, kernel_size)


def _gaussian_kernel_2d(channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32) -> Array:
    """(C, 1, kh, kw) depthwise gaussian kernel (reference: helper.py:29)."""
    kx = _gaussian(kernel_size[0], sigma[0], dtype)
    ky = _gaussian(kernel_size[1], sigma[1], dtype)
    kernel = kx.T @ ky  # (kh, kw)
    return jnp.broadcast_to(kernel, (channel, 1, kernel_size[0], kernel_size[1]))


def _gaussian_kernel_3d(
    channel: int, kernel_size: Sequence[int], sigma: Sequence[float], dtype=jnp.float32
) -> Array:
    """(C, 1, kd, kh, kw) depthwise gaussian kernel (reference: helper.py:~80)."""
    k2d = _gaussian_kernel_2d(channel, kernel_size[:2], sigma[:2], dtype)[0, 0]
    kz = _gaussian(kernel_size[2], sigma[2], dtype)[0]
    kernel = k2d[:, :, None] * kz[None, None, :]
    return jnp.broadcast_to(kernel, (channel, 1, *kernel.shape))


def _band_matrix(g: Array, npad: int) -> Array:
    """(npad, npad-K+1) banded matrix B with B[j+k, j] = g[k] — a VALID 1-D
    correlation expressed as a matmul."""
    k = g.shape[0]
    d = jnp.arange(npad)[:, None] - jnp.arange(npad - k + 1)[None, :]
    return jnp.where((d >= 0) & (d < k), g[jnp.clip(d, 0, k - 1)], 0.0).astype(g.dtype)


_SEPARABLE_MATMUL_MAX_DIM = 2048


def _separable_blur_2d(x: Array, g_h: Array, g_w: Array) -> Array:
    """VALID separable blur of x (N, C, Hp, Wp) via two banded matmuls on the MXU.

    TPU redesign of the depthwise gaussian/uniform window conv: XLA lowers the f32
    depthwise conv through multi-pass bf16 MXU passes (measured ~7e-4 absolute error
    and ~10 ms for a 16x15x266x266 SSIM stack), while the banded-matmul form at
    precision='float32' is f32-exact (~1.2e-7 vs float64 ground truth) and faster
    (1.7x at 256², still 1.4x at 1024² despite 17x the MACs) — MXU-shaped work
    beats grouped convolution on this hardware, and the exactness tightens SSIM
    parity with the f32-exact torch CPU reference.

    The band does O(H+W) MACs per pixel vs the conv's O(kh·kw), so beyond
    ``_SEPARABLE_MATMUL_MAX_DIM`` (measured crossover is past 1024; 2048 is a
    conservative bound) it falls back to the grouped conv.
    """
    if max(x.shape[-1], x.shape[-2]) > _SEPARABLE_MATMUL_MAX_DIM:
        kernel = jnp.broadcast_to(
            g_h[:, None] * g_w[None, :], (x.shape[1], 1, g_h.shape[0], g_w.shape[0])
        ).astype(x.dtype)
        return _depthwise_conv2d(x, kernel)
    bw = _band_matrix(g_w, x.shape[-1])
    bh = _band_matrix(g_h, x.shape[-2])
    y = jnp.einsum("nchw,wk->nchk", x, bw, precision="float32")
    return jnp.einsum("nchk,hj->ncjk", y, bh, precision="float32")


def _depthwise_conv2d(x: Array, kernel: Array) -> Array:
    """Grouped (per-channel) VALID conv: x (N,C,H,W), kernel (C,1,kh,kw)."""
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1),
        padding="VALID",
        feature_group_count=x.shape[1],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )


def _depthwise_conv3d(x: Array, kernel: Array) -> Array:
    return jax.lax.conv_general_dilated(
        x,
        kernel,
        window_strides=(1, 1, 1),
        padding="VALID",
        feature_group_count=x.shape[1],
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )


def _reflection_pad_2d(x: Array, pad_h: int, pad_w: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w)), mode="reflect")


def _reflection_pad_3d(x: Array, pad_d: int, pad_w: int, pad_h: int) -> Array:
    return jnp.pad(x, ((0, 0), (0, 0), (pad_h, pad_h), (pad_w, pad_w), (pad_d, pad_d)), mode="reflect")


def _avg_pool(x: Array, window: Tuple[int, ...]) -> Array:
    """Average pooling with stride == window (reference uses F.avg_pool2d/3d)."""
    nd = len(window)
    dims = (1, 1) + window
    out = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, dims, "VALID")
    return out / jnp.prod(jnp.asarray(window))


def _uniform_filter(x: Array, window_size: int) -> Array:
    """Same-size depthwise mean filter with symmetric padding (reference: helper.py:110,
    whose custom pad takes the first/last rows reversed = numpy 'symmetric')."""
    left = window_size // 2
    right = window_size - 1 - left
    x = jnp.pad(x, ((0, 0), (0, 0), (left, right), (left, right)), mode="symmetric")
    g = jnp.ones((window_size,), x.dtype) / window_size
    return _separable_blur_2d(x, g, g)
