"""Functional multimodal metrics (reference: src/torchmetrics/functional/multimodal/__init__.py)."""
from metrics_tpu.functional.multimodal.clip_score import clip_score

__all__ = ["clip_score"]
