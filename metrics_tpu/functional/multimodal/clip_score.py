"""CLIPScore functional (reference: functional/multimodal/clip_score.py:41-160).

Callable-encoder redesign: instead of hard-wiring the HF ``CLIPModel`` +
``CLIPProcessor`` pair, the encoder is a user-supplied pair of callables

    ``image_encoder(images [N, C, H, W]) -> (N, D)`` embeddings,
    ``text_encoder(captions: Sequence[str]) -> (N, D)`` embeddings

(unnormalized — L2 normalization happens here). When ``transformers`` is
installed and locally cached weights exist for ``model_name_or_path``, a default
encoder pair is built automatically. The score math is pure jnp:
``mean(max(100 * cos(E_I, E_C), 0))``.
"""
from typing import Callable, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _TRANSFORMERS_AVAILABLE

_DEFAULT_CLIP = "openai/clip-vit-large-patch14"

ImageEncoder = Callable[[Array], Array]
TextEncoder = Callable[[Sequence[str]], Array]


def _default_clip_encoders(model_name_or_path: str) -> Tuple[ImageEncoder, TextEncoder]:
    if not _TRANSFORMERS_AVAILABLE:
        raise ModuleNotFoundError(
            "`clip_score` with `model_name_or_path` requires the `transformers` package. Either install it or "
            "pass `image_encoder` and `text_encoder` callables."
        )
    import torch
    from transformers import CLIPModel, CLIPProcessor

    model = CLIPModel.from_pretrained(model_name_or_path)
    processor = CLIPProcessor.from_pretrained(model_name_or_path)
    model.eval()

    def image_encoder(images: Array) -> Array:
        batch = processor(images=[np.asarray(i) for i in images], return_tensors="pt")
        with torch.no_grad():
            feats = model.get_image_features(batch["pixel_values"])
        return jnp.asarray(feats.numpy())

    def text_encoder(captions: Sequence[str]) -> Array:
        batch = processor(text=list(captions), return_tensors="pt", padding=True)
        with torch.no_grad():
            feats = model.get_text_features(batch["input_ids"], batch["attention_mask"])
        return jnp.asarray(feats.numpy())

    return image_encoder, text_encoder


def _clip_score_from_features(img_features: Array, txt_features: Array) -> Array:
    """Per-sample ``100 * cos`` similarity — pure jnp, jit-safe."""
    img = img_features / jnp.maximum(jnp.linalg.norm(img_features, axis=-1, keepdims=True), 1e-30)
    txt = txt_features / jnp.maximum(jnp.linalg.norm(txt_features, axis=-1, keepdims=True), 1e-30)
    return 100.0 * jnp.sum(img * txt, axis=-1)


def _clip_score_update(
    images: Union[Array, List[Array]],
    text: Union[str, Sequence[str]],
    image_encoder: ImageEncoder,
    text_encoder: TextEncoder,
) -> Tuple[Array, int]:
    if isinstance(images, (list, tuple)):
        if not all(i.ndim == 3 for i in images):
            raise ValueError("Expected all images to be 3d but found image that has either more or less")
        images = jnp.stack([jnp.asarray(i) for i in images])
    elif images.ndim == 3:
        images = images[None]
    text_l = [text] if isinstance(text, str) else list(text)
    if len(text_l) != images.shape[0]:
        raise ValueError(
            f"Expected the number of images and text examples to be the same but got {images.shape[0]}"
            f" and {len(text_l)}"
        )
    score = _clip_score_from_features(image_encoder(images), text_encoder(text_l))
    return score, len(text_l)


def clip_score(
    images: Union[Array, List[Array]],
    text: Union[str, Sequence[str]],
    model_name_or_path: str = _DEFAULT_CLIP,
    image_encoder: Optional[ImageEncoder] = None,
    text_encoder: Optional[TextEncoder] = None,
) -> Array:
    """CLIPScore text-image alignment: ``mean(max(100 * cos(E_I, E_C), 0))``.

    Args:
        images: ``(N, C, H, W)`` array or list of ``(C, H, W)`` arrays.
        text: caption(s), one per image.
        model_name_or_path: HF CLIP checkpoint for the default encoders.
        image_encoder / text_encoder: custom embedding callables (both required
            together); see module docstring for the contract.
    """
    if (image_encoder is None) != (text_encoder is None):
        raise ValueError("`image_encoder` and `text_encoder` must be provided together.")
    if image_encoder is None:
        image_encoder, text_encoder = _default_clip_encoders(model_name_or_path)
    score, _ = _clip_score_update(images, text, image_encoder, text_encoder)
    score = score.mean(0)
    return jnp.maximum(score, jnp.zeros_like(score))
