from metrics_tpu.functional.detection.box_ops import (
    box_area,
    box_convert,
    box_iou,
    complete_box_iou,
    distance_box_iou,
    generalized_box_iou,
)
from metrics_tpu.functional.detection.ciou import complete_intersection_over_union
from metrics_tpu.functional.detection.diou import distance_intersection_over_union
from metrics_tpu.functional.detection.giou import generalized_intersection_over_union
from metrics_tpu.functional.detection.iou import intersection_over_union
from metrics_tpu.functional.detection.panoptic_qualities import modified_panoptic_quality, panoptic_quality

__all__ = [
    "box_area",
    "box_convert",
    "box_iou",
    "complete_box_iou",
    "complete_intersection_over_union",
    "distance_box_iou",
    "distance_intersection_over_union",
    "generalized_box_iou",
    "generalized_intersection_over_union",
    "intersection_over_union",
    "modified_panoptic_quality",
    "panoptic_quality",
]
