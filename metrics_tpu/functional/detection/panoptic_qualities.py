"""Panoptic Quality functionals (reference: functional/detection/panoptic_qualities.py:31-180)."""
from typing import Collection

from jax import Array

from metrics_tpu.functional.detection._panoptic_quality_common import (
    _get_category_id_to_continuous_id,
    _get_void_color,
    _panoptic_quality_compute,
    _panoptic_quality_update,
    _parse_categories,
    _preprocess_inputs,
    _validate_inputs,
)


def panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    r"""Compute Panoptic Quality for panoptic segmentations.

    ``PQ = IoU-sum / (TP + 0.5 FP + 0.5 FN)``, averaged over seen categories. Inputs
    are ``(B, *spatial, 2)`` tensors of ``(category_id, instance_id)`` pixels.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.detection import panoptic_quality
        >>> preds = jnp.array([[[[6, 0], [0, 0], [6, 0], [6, 0]],
        ...                     [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                     [[0, 0], [0, 0], [6, 0], [0, 1]],
        ...                     [[0, 0], [7, 0], [6, 0], [1, 0]],
        ...                     [[0, 0], [7, 0], [7, 0], [7, 0]]]])
        >>> target = jnp.array([[[[6, 0], [0, 1], [6, 0], [0, 1]],
        ...                      [[0, 1], [0, 1], [6, 0], [0, 1]],
        ...                      [[0, 1], [0, 1], [6, 0], [1, 0]],
        ...                      [[0, 1], [7, 0], [1, 0], [1, 0]],
        ...                      [[0, 1], [7, 0], [7, 0], [7, 0]]]])
        >>> float(panoptic_quality(preds, target, things={0, 1}, stuffs={6, 7}))  # doctest: +ELLIPSIS
        0.546...
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, true_positives, false_positives, false_negatives = _panoptic_quality_update(
        flatten_preds, flatten_target, cat_id_to_continuous_id, void_color
    )
    return _panoptic_quality_compute(iou_sum, true_positives, false_positives, false_negatives)


def modified_panoptic_quality(
    preds,
    target,
    things: Collection[int],
    stuffs: Collection[int],
    allow_unknown_preds_category: bool = False,
) -> Array:
    r"""Compute Modified Panoptic Quality: stuff classes use ``IoU-sum / num_segments``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.detection import modified_panoptic_quality
        >>> preds = jnp.array([[[0, 0], [0, 1], [6, 0], [7, 0], [0, 2], [1, 0]]])
        >>> target = jnp.array([[[0, 1], [0, 0], [6, 0], [7, 0], [6, 0], [255, 0]]])
        >>> float(modified_panoptic_quality(preds, target, things={0, 1}, stuffs={6, 7}))  # doctest: +ELLIPSIS
        0.766...
    """
    things, stuffs = _parse_categories(things, stuffs)
    _validate_inputs(preds, target)
    void_color = _get_void_color(things, stuffs)
    cat_id_to_continuous_id = _get_category_id_to_continuous_id(things, stuffs)
    flatten_preds = _preprocess_inputs(things, stuffs, preds, void_color, allow_unknown_preds_category)
    flatten_target = _preprocess_inputs(things, stuffs, target, void_color, True)
    iou_sum, true_positives, false_positives, false_negatives = _panoptic_quality_update(
        flatten_preds,
        flatten_target,
        cat_id_to_continuous_id,
        void_color,
        modified_metric_stuffs=stuffs,
    )
    return _panoptic_quality_compute(iou_sum, true_positives, false_positives, false_negatives)
