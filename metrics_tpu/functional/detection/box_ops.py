"""TPU-native pairwise box kernels.

The reference delegates box geometry to ``torchvision.ops`` (box_iou,
generalized_box_iou, distance_box_iou, complete_box_iou, box_convert — cited from
reference ``functional/detection/iou.py:21``, ``giou.py:21``, ``diou.py:21``,
``ciou.py:21``, ``detection/iou.py:28``). There is no torchvision on TPU; these are
from-scratch jnp implementations of the same math. Every kernel is a fused
broadcast-reduction over ``(N, 1, 4) x (1, M, 4)`` — XLA tiles the (N, M) result
onto the VPU in one pass, no host loop, no scatter.

All boxes are ``(x1, y1, x2, y2)`` with ``0 <= x1 < x2`` and ``0 <= y1 < y2``.
"""
from jax import Array
import jax.numpy as jnp

_EPS = 1e-7  # same stabilizer torchvision uses for the d/c-iou denominators


def box_convert(boxes: Array, in_fmt: str, out_fmt: str = "xyxy", xp=jnp) -> Array:
    """Convert ``(N, 4)`` boxes between ``xyxy``/``xywh``/``cxcywh`` formats.

    ``xp`` selects the array namespace (``jnp`` default; pass ``numpy`` to keep
    host inputs on host — mAP's update does, to avoid a device round trip).
    """
    if in_fmt == out_fmt:
        return boxes
    if out_fmt != "xyxy":
        raise ValueError(f"Only conversion to 'xyxy' is supported, got {out_fmt}")
    boxes = xp.asarray(boxes, xp.float32)
    a, b, c, d = boxes[..., 0], boxes[..., 1], boxes[..., 2], boxes[..., 3]
    if in_fmt == "xywh":
        return xp.stack([a, b, a + c, b + d], axis=-1)
    if in_fmt == "cxcywh":
        return xp.stack([a - c / 2, b - d / 2, a + c / 2, b + d / 2], axis=-1)
    raise ValueError(f"Unsupported box format {in_fmt!r}; expected one of ('xyxy', 'xywh', 'cxcywh')")


def box_area(boxes: Array) -> Array:
    """Areas of ``(..., 4)`` xyxy boxes."""
    boxes = jnp.asarray(boxes, jnp.float32)
    return (boxes[..., 2] - boxes[..., 0]) * (boxes[..., 3] - boxes[..., 1])


def _inter_union(preds: Array, target: Array):
    """Pairwise intersection and union: ``(N, 4), (M, 4) -> (N, M), (N, M)``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    lt = jnp.maximum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.minimum(preds[:, None, 2:], target[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(preds)[:, None] + box_area(target)[None, :] - inter
    return inter, union


def box_iou(preds: Array, target: Array) -> Array:
    """Pairwise IoU matrix ``(N, M)``."""
    inter, union = _inter_union(preds, target)
    return jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)


def _enclosing_wh(preds: Array, target: Array) -> Array:
    """Width/height of the smallest box enclosing each pair: ``(N, M, 2)``."""
    lt = jnp.minimum(preds[:, None, :2], target[None, :, :2])
    rb = jnp.maximum(preds[:, None, 2:], target[None, :, 2:])
    return jnp.clip(rb - lt, 0)


def generalized_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise GIoU matrix: ``iou - (enclosing_area - union) / enclosing_area``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    inter, union = _inter_union(preds, target)
    iou = inter / union
    whi = _enclosing_wh(preds, target)
    enclosing = whi[..., 0] * whi[..., 1]
    return iou - (enclosing - union) / enclosing


def _diou_iou(preds: Array, target: Array):
    iou = box_iou(preds, target)
    whi = _enclosing_wh(preds, target)
    diag_sq = whi[..., 0] ** 2 + whi[..., 1] ** 2 + _EPS
    cx_p = (preds[:, 0] + preds[:, 2]) / 2
    cy_p = (preds[:, 1] + preds[:, 3]) / 2
    cx_t = (target[:, 0] + target[:, 2]) / 2
    cy_t = (target[:, 1] + target[:, 3]) / 2
    center_sq = (cx_p[:, None] - cx_t[None, :]) ** 2 + (cy_p[:, None] - cy_t[None, :]) ** 2
    return iou - center_sq / diag_sq, iou


def distance_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise DIoU matrix: ``iou - center_distance² / enclosing_diagonal²``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    diou, _ = _diou_iou(preds, target)
    return diou


def complete_box_iou(preds: Array, target: Array) -> Array:
    """Pairwise CIoU matrix: ``diou - alpha * v`` with the aspect-ratio term ``v``."""
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    diou, iou = _diou_iou(preds, target)
    w_p = preds[:, 2] - preds[:, 0]
    h_p = preds[:, 3] - preds[:, 1]
    w_t = target[:, 2] - target[:, 0]
    h_t = target[:, 3] - target[:, 1]
    v = (4 / jnp.pi**2) * (jnp.arctan(w_t / h_t)[None, :] - jnp.arctan(w_p / h_p)[:, None]) ** 2
    alpha = v / (1 - iou + v + _EPS)
    return diou - alpha * v
