"""Shared panoptic-quality machinery.

Behavioral parity with reference ``functional/detection/_panoptic_quality_common.py``
(``_panoptic_quality_update_sample`` :300-381, ``_panoptic_quality_compute`` :433-454),
re-designed for TPU: the reference walks Python dicts keyed by ``(category_id,
instance_id)`` tuples and loops over every pred x target intersection pair. Here each
sample's segments are relabeled to dense ids once (host ``np.unique`` — segment count
is data-dependent, so this step cannot be static-shaped), and everything after that is
a single ``(num_pred_segments, num_target_segments)`` intersection matrix built by one
bincount over encoded pair-ids, with the matching / TP / FP / FN logic as fused
vectorized masks over that matrix instead of per-pair Python branching.
"""
from typing import Collection, Dict, Optional, Set, Tuple

import jax
from jax import Array
import jax.numpy as jnp
import numpy as np

from metrics_tpu.utils.prints import rank_zero_warn

_Color = Tuple[int, int]


def _f64() -> jnp.dtype:
    """Reference accumulates in double (:334); match it under x64, else f32."""
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _parse_categories(things: Collection[int], stuffs: Collection[int]) -> Tuple[Set[int], Set[int]]:
    """Validate the ``things``/``stuffs`` category sets (reference :62-89)."""
    things_parsed = set(things)
    if len(things_parsed) < len(things):
        rank_zero_warn("The provided `things` categories contained duplicates, which have been removed.", UserWarning)
    stuffs_parsed = set(stuffs)
    if len(stuffs_parsed) < len(stuffs):
        rank_zero_warn("The provided `stuffs` categories contained duplicates, which have been removed.", UserWarning)
    if not all(isinstance(val, int) for val in things_parsed):
        raise TypeError(f"Expected argument `things` to contain `int` categories, but got {things}")
    if not all(isinstance(val, int) for val in stuffs_parsed):
        raise TypeError(f"Expected argument `stuffs` to contain `int` categories, but got {stuffs}")
    if things_parsed & stuffs_parsed:
        raise ValueError(
            f"Expected arguments `things` and `stuffs` to have distinct keys, but got {things} and {stuffs}"
        )
    if not (things_parsed | stuffs_parsed):
        raise ValueError("At least one of `things` and `stuffs` must be non-empty.")
    return things_parsed, stuffs_parsed


def _validate_inputs(preds, target) -> None:
    """Shape validation (reference :92-116)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if preds.shape != target.shape:
        raise ValueError(
            f"Expected argument `preds` and `target` to have the same shape, but got {preds.shape} and {target.shape}"
        )
    if preds.ndim < 3:
        raise ValueError(
            "Expected argument `preds` to have at least one spatial dimension (B, *spatial_dims, 2), "
            f"got {preds.shape}"
        )
    if preds.shape[-1] != 2:
        raise ValueError(
            "Expected argument `preds` to have exactly 2 channels in the last dimension (category, instance), "
            f"got {preds.shape} instead"
        )


def _get_void_color(things: Set[int], stuffs: Set[int]) -> Tuple[int, int]:
    """An unused (category, instance) color (reference :119-130)."""
    unused_category_id = 1 + max([0] + list(things) + list(stuffs))
    return unused_category_id, 0


def _get_category_id_to_continuous_id(things: Set[int], stuffs: Set[int]) -> Dict[int, int]:
    """Map original category ids to dense ids, things first (reference :133-150)."""
    thing_id_to_continuous_id = {thing_id: idx for idx, thing_id in enumerate(things)}
    stuff_id_to_continuous_id = {stuff_id: idx + len(things) for idx, stuff_id in enumerate(stuffs)}
    cat_id_to_continuous_id = {}
    cat_id_to_continuous_id.update(thing_id_to_continuous_id)
    cat_id_to_continuous_id.update(stuff_id_to_continuous_id)
    return cat_id_to_continuous_id


def _preprocess_inputs(
    things: Set[int],
    stuffs: Set[int],
    inputs,
    void_color: Tuple[int, int],
    allow_unknown_category: bool,
) -> np.ndarray:
    """Flatten spatial dims, zero stuff instance-ids, map unknown cats to void.

    Reference ``_prepocess_inputs`` :167-202 (sic). Returns host ``(B, P, 2)`` int64.
    """
    out = np.array(inputs, dtype=np.int64, copy=True)
    out = out.reshape(out.shape[0], -1, 2)
    mask_stuffs = np.isin(out[:, :, 0], list(stuffs))
    mask_things = np.isin(out[:, :, 0], list(things))
    out[:, :, 1] = np.where(mask_stuffs, 0, out[:, :, 1])
    unknown = ~(mask_things | mask_stuffs)
    if not allow_unknown_category and unknown.any():
        raise ValueError(f"Unknown categories found: {out[unknown]}")
    out[:, :, 0] = np.where(unknown, void_color[0], out[:, :, 0])
    out[:, :, 1] = np.where(unknown, void_color[1], out[:, :, 1])
    return out


def _panoptic_quality_update_sample(
    flatten_preds: np.ndarray,
    flatten_target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    stuffs_modified_metric: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Per-sample stat scores: (iou_sum, TP, FP, FN) per continuous category.

    Parity target: reference ``_panoptic_quality_update_sample`` :300-381. The whole
    pred x target matching is vectorized over the dense ``(Np, Nt)`` intersection
    matrix; segments match when categories agree and IoU > 0.5 (IoU > 0.5 matches are
    provably unique, so no greedy loop is needed).
    """
    stuffs_modified_metric = stuffs_modified_metric or set()
    n_categories = len(cat_id_to_continuous_id)

    # dense relabel: color (cat, inst) -> segment id (host; counts are data-dependent)
    enc = np.int64(1) << np.int64(32)
    pred_keys = flatten_preds[:, 0] * enc + flatten_preds[:, 1]
    target_keys = flatten_target[:, 0] * enc + flatten_target[:, 1]
    pred_colors, pred_ids = np.unique(pred_keys, return_inverse=True)
    target_colors, target_ids = np.unique(target_keys, return_inverse=True)
    num_p, num_t = len(pred_colors), len(target_colors)
    pred_cat = (pred_colors // enc).astype(np.int64)
    target_cat = (target_colors // enc).astype(np.int64)

    void_key = np.int64(void_color[0]) * enc + np.int64(void_color[1])
    p_void = pred_colors == void_key  # (Np,) one-hot at most
    t_void = target_colors == void_key

    # areas + intersection matrix: one fused bincount over encoded pair ids
    pair_ids = jnp.asarray(pred_ids) * num_t + jnp.asarray(target_ids)
    inter = jnp.bincount(pair_ids, length=num_p * num_t).reshape(num_p, num_t).astype(_f64())
    pred_area = inter.sum(axis=1)  # == bincount(pred_ids); reuse the matrix
    target_area = inter.sum(axis=0)

    # IoU with void-corrected union (reference ``_calculate_iou`` :205-241)
    pred_void_area = jnp.where(jnp.asarray(t_void).any(), inter[:, jnp.argmax(jnp.asarray(t_void))], 0.0)
    void_target_area = jnp.where(jnp.asarray(p_void).any(), inter[jnp.argmax(jnp.asarray(p_void)), :], 0.0)
    union = pred_area[:, None] - pred_void_area[:, None] + target_area[None, :] - void_target_area[None, :] - inter
    iou = jnp.where(union > 0, inter / jnp.where(union > 0, union, 1.0), 0.0)

    same_cat = jnp.asarray(pred_cat)[:, None] == jnp.asarray(target_cat)[None, :]
    considered = same_cat & (inter > 0) & ~jnp.asarray(t_void)[None, :] & ~jnp.asarray(p_void)[:, None]

    modified_stuff_cat = np.isin(target_cat, list(stuffs_modified_metric)) if stuffs_modified_metric else np.zeros(
        num_t, dtype=bool
    )
    modified_stuff_pair = jnp.asarray(modified_stuff_cat)[None, :]

    matched = considered & (iou > 0.5) & ~modified_stuff_pair
    modified_matched = considered & (iou > 0) & modified_stuff_pair

    # continuous-id lookup for each target/pred segment (host dict -> dense map)
    cont_of = np.full(max(cat_id_to_continuous_id) + 2, -1, dtype=np.int64)
    for cat, cont in cat_id_to_continuous_id.items():
        cont_of[cat] = cont
    target_cont = jnp.asarray(np.where((target_cat >= 0) & (target_cat < len(cont_of)), cont_of[np.clip(target_cat, 0, len(cont_of) - 1)], -1))
    pred_cont = jnp.asarray(np.where((pred_cat >= 0) & (pred_cat < len(cont_of)), cont_of[np.clip(pred_cat, 0, len(cont_of) - 1)], -1))

    pair_cont = jnp.broadcast_to(target_cont[None, :], matched.shape)
    iou_contrib = jnp.where(matched | modified_matched, iou, 0.0)
    iou_sum = jnp.zeros(n_categories, _f64()).at[jnp.clip(pair_cont, 0)].add(
        jnp.where(pair_cont >= 0, iou_contrib, 0.0)
    )
    true_positives = jnp.zeros(n_categories, jnp.int32).at[jnp.clip(pair_cont, 0)].add(
        jnp.where(pair_cont >= 0, matched, False).astype(jnp.int32)
    )

    # FN: unmatched non-void target segments that are not mostly void in the pred
    target_matched = matched.any(axis=0)
    mostly_void_t = void_target_area > 0.5 * target_area
    fn_mask = (
        ~target_matched
        & ~jnp.asarray(t_void)
        & ~mostly_void_t
        & ~jnp.asarray(modified_stuff_cat)
        & (target_cont >= 0)
    )
    false_negatives = jnp.zeros(n_categories, jnp.int32).at[jnp.clip(target_cont, 0)].add(fn_mask.astype(jnp.int32))

    # FP: unmatched non-void pred segments that are not mostly void in the target
    pred_matched = matched.any(axis=1)
    mostly_void_p = pred_void_area > 0.5 * pred_area
    modified_stuff_pred = (
        jnp.asarray(np.isin(pred_cat, list(stuffs_modified_metric))) if stuffs_modified_metric else jnp.zeros(num_p, bool)
    )
    fp_mask = ~pred_matched & ~jnp.asarray(p_void) & ~mostly_void_p & ~modified_stuff_pred & (pred_cont >= 0)
    false_positives = jnp.zeros(n_categories, jnp.int32).at[jnp.clip(pred_cont, 0)].add(fp_mask.astype(jnp.int32))

    # modified PQ: TP counts every target segment of a modified-stuff category
    if stuffs_modified_metric:
        seg_mask = jnp.asarray(modified_stuff_cat) & (target_cont >= 0)
        true_positives = true_positives.at[jnp.clip(target_cont, 0)].add(seg_mask.astype(jnp.int32))

    return iou_sum, true_positives, false_positives, false_negatives


def _panoptic_quality_update(
    flatten_preds: np.ndarray,
    flatten_target: np.ndarray,
    cat_id_to_continuous_id: Dict[int, int],
    void_color: Tuple[int, int],
    modified_metric_stuffs: Optional[Set[int]] = None,
) -> Tuple[Array, Array, Array, Array]:
    """Batch stat scores (reference :384-430). Segments never match across samples."""
    n_categories = len(cat_id_to_continuous_id)
    iou_sum = jnp.zeros(n_categories, _f64())
    true_positives = jnp.zeros(n_categories, jnp.int32)
    false_positives = jnp.zeros(n_categories, jnp.int32)
    false_negatives = jnp.zeros(n_categories, jnp.int32)

    for preds_single, target_single in zip(flatten_preds, flatten_target):
        result = _panoptic_quality_update_sample(
            preds_single,
            target_single,
            cat_id_to_continuous_id,
            void_color,
            stuffs_modified_metric=modified_metric_stuffs,
        )
        iou_sum = iou_sum + result[0]
        true_positives = true_positives + result[1]
        false_positives = false_positives + result[2]
        false_negatives = false_negatives + result[3]

    return iou_sum, true_positives, false_positives, false_negatives


def _panoptic_quality_compute(
    iou_sum: Array,
    true_positives: Array,
    false_positives: Array,
    false_negatives: Array,
) -> Array:
    """PQ = IoU-sum / (TP + FP/2 + FN/2), averaged over seen categories (reference :433-454)."""
    denominator = (true_positives + 0.5 * false_positives + 0.5 * false_negatives).astype(_f64())
    panoptic_quality = jnp.where(denominator > 0.0, iou_sum / jnp.where(denominator > 0, denominator, 1.0), 0.0)
    seen = denominator > 0
    return jnp.where(seen.any(), panoptic_quality.sum() / jnp.clip(seen.sum(), 1), jnp.nan)
