"""Root-functional deprecation shims (reference: functional/detection/_deprecated.py).

``metrics_tpu.functional.<name>`` warns; ``metrics_tpu.functional.detection.<name>``
stays silent (reference utilities/prints.py:67-72).
"""
from metrics_tpu.functional.detection import modified_panoptic_quality, panoptic_quality
from metrics_tpu.utils.prints import _root_func_shim

_modified_panoptic_quality = _root_func_shim(modified_panoptic_quality, "modified_panoptic_quality", "detection")
_panoptic_quality = _root_func_shim(panoptic_quality, "panoptic_quality", "detection")

__all__ = ["_modified_panoptic_quality", "_panoptic_quality"]
