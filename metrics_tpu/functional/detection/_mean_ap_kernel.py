"""Vectorized COCO-matching kernel for MeanAveragePrecision.

The reference evaluates detections with a Python triple loop — per image x class x
IoU-threshold greedy matching (``detection/mean_ap.py:509-606``, ``_find_best_gt_match``
:608-635) — the hottest CPU loop in the whole library. Here the greedy match is a
single ``lax.scan`` over score-sorted detections (the only true sequential dependency),
with all IoU thresholds evaluated simultaneously as a vectorized ``(T, G)`` mask
update, ``vmap``-ed over COCO area ranges and again over all (image, class) evaluation
groups. Shapes are static (padded to power-of-two buckets by the caller), so XLA
compiles one fused kernel that runs entirely on device.

Two entry points share the matching core: ``_match_groups`` computes box IoU/areas
itself; ``_match_groups_from_iou`` takes precomputed IoU + areas (the segm path —
dense mask IoU is a matmul, so no pycocotools RLE is needed; reference
``detection/mean_ap.py:345`` requires it).
"""
from metrics_tpu.utils.data import _next_pow2

import jax
from jax import Array
import jax.numpy as jnp

from metrics_tpu.functional.detection.box_ops import box_area, box_iou


def _per_group_from_iou(iou, d_area, g_area, dv, gv, iou_thresholds, area_ranges):
    """Greedy matching for one group given its (D, G) IoU and element areas."""
    num_t = iou_thresholds.shape[0]
    num_g = iou.shape[1]
    iou = jnp.where(dv[:, None] & gv[None, :], iou, 0.0)

    def per_area(rng):
        lo, hi = rng[0], rng[1]
        g_ignore_area = (g_area < lo) | (g_area > hi)
        # parity: reference sorts gts ignored-last before matching (:558-564)
        sort_key = g_ignore_area.astype(jnp.int32) + 2 * (~gv).astype(jnp.int32)
        perm = jnp.argsort(sort_key, stable=True)
        iou_p = iou[:, perm]
        g_ignore = (g_ignore_area | ~gv)[perm]  # (G,)

        def step(gt_matches, inp):
            # one detection, all T thresholds at once; ignored gts never match
            # (parity with reference _find_best_gt_match :628-635)
            row, valid_d = inp
            remove = gt_matches | g_ignore[None, :]
            cand = jnp.where(remove, 0.0, row[None, :])  # (T, G)
            m = jnp.argmax(cand, axis=1)
            best = jnp.take_along_axis(cand, m[:, None], axis=1)[:, 0]
            matched = (best > iou_thresholds) & valid_d
            hit = (jnp.arange(num_g)[None, :] == m[:, None]) & matched[:, None]
            return gt_matches | hit, matched

        gt_matches0 = jnp.zeros((num_t, num_g), bool)
        _, det_matched = jax.lax.scan(step, gt_matches0, (iou_p, dv))
        det_matched = det_matched.T  # (T, D)
        d_outside = (d_area < lo) | (d_area > hi)
        # unmatched out-of-range dets are ignored (:592-598); padding is always ignored
        det_ignored = (~det_matched & d_outside[None, :]) | ~dv[None, :]
        npig = jnp.sum(gv & ~g_ignore_area)
        return det_matched, det_ignored, npig

    return jax.vmap(per_area)(area_ranges)


def _match_groups_core(
    det_boxes: Array,   # (N, D, 4) score-sorted per group, zero-padded
    det_valid: Array,   # (N, D) bool
    gt_boxes: Array,    # (N, G, 4) zero-padded
    gt_valid: Array,    # (N, G) bool
    iou_thresholds: Array,  # (T,)
    area_ranges: Array,     # (A, 2) [lo, hi] area bounds
):
    """Box matching for all groups x area ranges x IoU thresholds at once.

    Returns ``det_matched (N, A, T, D)``, ``det_ignored (N, A, T, D)`` and
    ``npig (N, A)`` — the number of non-ignored ground truths per group/area.
    Unjitted so the fully-device consolidated pipeline (_mean_ap_device.py) can
    inline it inside its own program; the legacy host-orchestrated path uses the
    jitted ``_match_groups`` wrapper below.
    """

    def per_group(db, dv, gb, gv):
        return _per_group_from_iou(box_iou(db, gb), box_area(db), box_area(gb), dv, gv, iou_thresholds, area_ranges)

    return jax.vmap(per_group)(det_boxes, det_valid, gt_boxes, gt_valid)


_match_groups = jax.jit(_match_groups_core)


@jax.jit
def _match_groups_from_iou(
    iou: Array,        # (N, D, G) precomputed per-group IoU, score-sorted rows
    d_area: Array,     # (N, D)
    g_area: Array,     # (N, G)
    det_valid: Array,  # (N, D) bool
    gt_valid: Array,   # (N, G) bool
    iou_thresholds: Array,
    area_ranges: Array,
):
    """Same matching from precomputed IoU/areas (mask IoU for ``iou_type="segm"``)."""

    def per_group(i, da, ga, dv, gv):
        return _per_group_from_iou(i, da, ga, dv, gv, iou_thresholds, area_ranges)

    return jax.vmap(per_group)(iou, d_area, g_area, det_valid, gt_valid)


_pow2 = _next_pow2  # shared bucketing helper (utils/data.py)
