"""IoU functional (reference: functional/detection/iou.py:29-81)."""
from typing import Optional

from jax import Array
import jax.numpy as jnp

from metrics_tpu.functional.detection.box_ops import box_iou


def _iou_update(preds: Array, target: Array, iou_threshold: Optional[float], replacement_val: float = 0) -> Array:
    iou = box_iou(preds, target)
    if iou_threshold is not None:
        iou = jnp.where(iou < iou_threshold, replacement_val, iou)
    return iou


def _iou_compute(iou: Array, labels_eq: bool = True) -> Array:
    if labels_eq:
        return jnp.diagonal(iou).mean()
    return iou.mean()


def intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """Compute Intersection over Union between two sets of xyxy boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.detection import intersection_over_union
        >>> preds = jnp.array([[100.0, 100.0, 200.0, 200.0]])
        >>> target = jnp.array([[110.0, 110.0, 210.0, 210.0]])
        >>> intersection_over_union(preds, target)
        Array(0.6806723, dtype=float32)
    """
    iou = _iou_update(preds, target, iou_threshold, replacement_val)
    return _iou_compute(iou) if aggregate else iou
