"""DIoU functional (reference: functional/detection/diou.py:30-82)."""
from typing import Optional

from jax import Array
import jax.numpy as jnp

from metrics_tpu.functional.detection.box_ops import distance_box_iou


def _diou_update(preds: Array, target: Array, iou_threshold: Optional[float], replacement_val: float = 0) -> Array:
    iou = distance_box_iou(preds, target)
    if iou_threshold is not None:
        iou = jnp.where(iou < iou_threshold, replacement_val, iou)
    return iou


def _diou_compute(iou: Array, labels_eq: bool = True) -> Array:
    if labels_eq:
        return jnp.diagonal(iou).mean()
    return iou.mean()


def distance_intersection_over_union(
    preds: Array,
    target: Array,
    iou_threshold: Optional[float] = None,
    replacement_val: float = 0,
    aggregate: bool = True,
) -> Array:
    """Compute Distance Intersection over Union between two sets of xyxy boxes.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.detection import distance_intersection_over_union
        >>> preds = jnp.array([[100.0, 100.0, 200.0, 200.0]])
        >>> target = jnp.array([[110.0, 110.0, 210.0, 210.0]])
        >>> distance_intersection_over_union(preds, target)
        Array(0.6724078, dtype=float32)
    """
    iou = _diou_update(preds, target, iou_threshold, replacement_val)
    return _diou_compute(iou) if aggregate else iou
