"""Fully device-resident mAP evaluation for consolidated inputs.

The reference's evaluation is host-orchestrated end to end: python loops build
per-(image, class) tensors, the matching loop runs on CPU, and the PR tables come
from numpy (``/root/reference/src/torchmetrics/detection/mean_ap.py:509-606,773-840``).
Round 4's port moved the matching loop onto the device but still round-tripped all
per-image data host->group-tensors->device and the (N, A, T, D) match masks back —
on a ~25-50 MB/s tunneled backend those two transfers plus the padded-shape kernel
were ~3 s of a ~4 s cycle for 1000 images (measured: experiments/map_profile2.py).

This module removes the data movement entirely for the consolidated input layout
(update appends ``(B, M, ...)`` padded batches — the natural output shape of a TPU
detection model). Everything from grouping to the 101-point PR tables runs in ONE
jitted program over the buffers already in HBM:

1. **Grouping is a batched stable sort**, not a python loop: for each class, each
   image's rows are ordered by ``(label != k, -score)`` so the class's detections
   land score-sorted in the leading slots (parity with the reference's
   ``argsort(-scores, stable)[:max_det]``).
2. **Two-bucket matching**: the greedy-match scan costs O(D) sequential steps and
   O(G) per-step width, and measured time is ~linear in both (D=128,G=64 ->
   D=16,G=16 is 7.4x: experiments/map_kernel_exp.py). Nearly every (image, class)
   group is small, so groups with <= 16 dets and <= 16 gts run in a (K*B)-wide
   D=16/G=16 kernel and only the rare big groups pay the wide shapes. The split
   is decided on host from a ~0.5 MB label fetch; bucket shapes are pow2 so
   compile keys stay log-bounded.
3. **PR accumulation on device** (``lax.map`` over classes to bound memory): per
   class, all row slots (small grid + masked big-bucket rows) are score-sorted
   once, and tps/fps cumsums, precision envelope (reverse cummax) and the
   101-recall-threshold lookup (vectorized searchsorted) produce the final
   ``(T, R, K, A, M)`` table. Cumsums are f32 but exact: summands are 0/1 counts
   and every partial sum is an integer < 2^24 for < 16.7M detections per class.
   Only the ~0.25 MB tables cross the tunnel.

Parity with the host path is exact up to f32-vs-f64 division rounding in rc/pr
(<= ~1e-7 relative; the bench asserts <= 1e-6 vs the live reference) and score-tie
ordering between rows of different buckets (pycocotools itself is permutation-
dependent under ties).
"""
from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.detection._mean_ap_kernel import _match_groups_core
from metrics_tpu.utils.data import _next_pow2

_EPS = float(np.finfo(np.float64).eps)


def _group_rows(boxes, scores, labels, class_vec, width, max_det):
    """Score-sorted class rows for each (group, class) pair.

    ``boxes/scores/labels`` are ``(N, M, ...)`` image rows; ``class_vec`` is the
    ``(N,)`` class id each output group selects. Returns ``(N, width)`` slots:
    the class's detections score-sorted first (stable ties keep input order, as
    the reference's ``argsort(-scores, kind="stable")``), padding after;
    ``valid`` marks real class rows within the top ``max_det``.
    """
    is_class = labels == class_vec[:, None]
    key = jnp.where(is_class, -scores, jnp.inf)
    perm = jnp.argsort(key, axis=1, stable=True)[:, :width]
    b = jnp.take_along_axis(boxes, perm[..., None], axis=1)
    s = jnp.take_along_axis(scores, perm, axis=1)
    valid = jnp.take_along_axis(is_class, perm, axis=1)
    valid = valid & (jnp.arange(width)[None, :] < max_det)
    return b, s, valid


def _group_gt_rows(boxes, labels, class_vec, width):
    """Class ground-truth rows packed first (original order preserved)."""
    is_class = labels == class_vec[:, None]
    perm = jnp.argsort(~is_class, axis=1, stable=True)[:, :width]
    b = jnp.take_along_axis(boxes, perm[..., None], axis=1)
    valid = jnp.take_along_axis(is_class, perm, axis=1)
    return b, valid


@partial(
    jax.jit,
    static_argnames=("d_small", "g_small", "d_big", "g_big", "max_det", "caps"),
)
def consolidated_tables(
    det_boxes: Array,   # (B, M, 4) xyxy
    det_scores: Array,  # (B, M); padding rows score -inf
    det_labels: Array,  # (B, M) int32; padding rows < 0
    gt_boxes: Array,    # (B, Mg, 4)
    gt_labels: Array,   # (B, Mg) int32; padding rows < 0
    class_arr: Array,   # (K,) int32 sorted unique class ids
    is_small: Array,    # (B, K) bool: group (b, k) routed to the small bucket
    big_b: Array,       # (Nb,) int32 image index of each big group (0 for dummies)
    big_k: Array,       # (Nb,) int32 class id of each big group (-1 for dummies)
    big_kidx: Array,    # (Nb,) int32 index into class_arr (-1 for dummies)
    iou_thresholds: Array,  # (T,)
    rec_thresholds: Array,  # (R,)
    area_ranges: Array,     # (A, 2)
    *,
    d_small: int,
    g_small: int,
    d_big: int,
    g_big: int,
    max_det: int,
    caps: Tuple[int, ...],
) -> Tuple[Array, Array]:
    """Precision ``(T, R, K, A, M)`` and recall ``(T, K, A, M)`` tables on device."""
    B, K = is_small.shape
    num_t = iou_thresholds.shape[0]
    num_a = area_ranges.shape[0]
    num_m = len(caps)

    # ---- small bucket: dense (K, B) grid of groups at narrow widths ----------
    def small_class(k, small_k):
        db, ds, dv = _group_rows(det_boxes, det_scores, det_labels, jnp.full((B,), k), d_small, max_det)
        gb, gv = _group_gt_rows(gt_boxes, gt_labels, jnp.full((B,), k), g_small)
        dv = dv & small_k[:, None]
        gv = gv & small_k[:, None]
        return db, ds, dv, gb, gv

    s_db, s_ds, s_dv, s_gb, s_gv = jax.vmap(small_class)(class_arr, is_small.T)  # (K, B, ...)
    flat = lambda x: x.reshape((K * B,) + x.shape[2:])
    s_matched, s_ignored, s_npig = _match_groups_core(
        flat(s_db), flat(s_dv), flat(s_gb), flat(s_gv), iou_thresholds, area_ranges
    )  # (K*B, A, T, d_small), ..., (K*B, A)
    s_matched = s_matched.reshape(K, B, num_a, num_t, d_small)
    s_ignored = s_ignored.reshape(K, B, num_a, num_t, d_small)
    s_npig = s_npig.reshape(K, B, num_a)
    s_scores = s_ds  # (K, B, d_small)

    # ---- big bucket: host-listed (b, k) groups at wide static widths ---------
    nb = big_b.shape[0]
    b_db, b_ds, b_dv = _group_rows(
        det_boxes[big_b], det_scores[big_b], det_labels[big_b], big_k, d_big, max_det
    )
    b_gb, b_gv = _group_gt_rows(gt_boxes[big_b], gt_labels[big_b], big_k, g_big)
    # dummy groups carry class -1, which matches padding label rows: mask them out
    real = (big_k >= 0)[:, None]
    b_dv = b_dv & real
    b_gv = b_gv & real
    b_matched, b_ignored, b_npig = _match_groups_core(
        b_db, b_dv, b_gb, b_gv, iou_thresholds, area_ranges
    )  # (Nb, A, T, d_big), ..., (Nb, A)

    # per-class npig: small grid sum + big groups folded in by class index
    npig = s_npig.sum(axis=1)  # (K, A)
    npig = npig + jax.ops.segment_sum(
        b_npig * (big_kidx >= 0)[:, None], jnp.maximum(big_kidx, 0), num_segments=K
    )

    caps_arr = jnp.asarray(caps, jnp.int32)  # (M,)
    num_r = rec_thresholds.shape[0]

    # ---- PR accumulation: one class at a time (lax.map bounds peak memory) ---
    def per_class(kidx):
        # rows = the class's small grid slots + every big-bucket slot masked to it
        sc = jnp.concatenate([s_scores[kidx].reshape(-1), b_ds.reshape(-1)])
        rank = jnp.concatenate(
            [
                jnp.broadcast_to(jnp.arange(d_small), (B, d_small)).reshape(-1),
                jnp.broadcast_to(jnp.arange(d_big), (nb, d_big)).reshape(-1),
            ]
        )
        mine = big_kidx == kidx  # (Nb,)
        m_rows = jnp.concatenate(
            [
                s_matched[kidx].transpose(0, 3, 1, 2).reshape(B * d_small, num_a, num_t),
                b_matched.transpose(0, 3, 1, 2).reshape(nb * d_big, num_a, num_t),
            ]
        )  # (R, A, T)
        i_rows = jnp.concatenate(
            [
                s_ignored[kidx].transpose(0, 3, 1, 2).reshape(B * d_small, num_a, num_t),
                (b_ignored | ~mine[:, None, None, None]).transpose(0, 3, 1, 2).reshape(nb * d_big, num_a, num_t),
            ]
        )
        other = jnp.concatenate([jnp.zeros(B * d_small, bool), ~mine.repeat(d_big)])
        sc = jnp.where(other, -jnp.inf, sc)

        order = jnp.argsort(-sc, stable=True)
        rank = rank[order]
        m_rows = m_rows[order]
        i_rows = i_rows[order]

        incap = rank[:, None] < caps_arr[None, :]  # (R, M)
        counted = ~i_rows  # (R, A, T)
        # (A, T, M, R) streams; 0/1 summands keep f32 cumsums exact below 2^24 rows
        tp = (m_rows & counted)[:, :, :, None] & incap[:, None, None, :]
        fp = (~m_rows & counted)[:, :, :, None] & incap[:, None, None, :]
        tps = jnp.cumsum(tp.transpose(1, 2, 3, 0).astype(jnp.float32), axis=-1)
        fps = jnp.cumsum(fp.transpose(1, 2, 3, 0).astype(jnp.float32), axis=-1)

        npig_k = npig[kidx]  # (A,)
        rc = tps / jnp.maximum(npig_k[:, None, None, None], 1.0)
        pr = tps / (tps + fps + _EPS)
        rec_last = rc[..., -1]  # (A, T, M)
        pr_env = jax.lax.cummax(pr[..., ::-1], axis=pr.ndim - 1)[..., ::-1]

        flat_rc = rc.reshape(-1, rc.shape[-1])
        inds = jax.vmap(lambda row: jnp.searchsorted(row, rec_thresholds, side="left"))(flat_rc)
        flat_env = pr_env.reshape(-1, pr_env.shape[-1])
        n_rows = flat_rc.shape[-1]
        prec = jnp.where(
            inds < n_rows,
            jnp.take_along_axis(flat_env, jnp.minimum(inds, n_rows - 1), axis=-1),
            0.0,
        )  # (A*T*M, R_thr)
        prec = prec.reshape(num_a, num_t, num_m, num_r)

        # npig == 0 keeps the reference's -1 sentinel for "no gts in this slice"
        valid = npig_k > 0  # (A,)
        prec = jnp.where(valid[:, None, None, None], prec, -1.0)
        rec_last = jnp.where(valid[:, None, None], rec_last, -1.0)
        return prec, rec_last

    prec_k, rec_k = jax.lax.map(per_class, jnp.arange(K))  # (K, A, T, M, R), (K, A, T, M)
    precision = prec_k.transpose(2, 4, 0, 1, 3)  # (T, R, K, A, M)
    recall = rec_k.transpose(2, 0, 1, 3)         # (T, K, A, M)
    return precision, recall


def plan_buckets(det_counts: np.ndarray, gt_counts: np.ndarray, max_det: int):
    """Host-side bucket routing from per-(image, class) row counts.

    Returns ``(is_small (B, K) bool, big_pairs list[(b, kidx)], d_big, g_big)``
    with pow2 widths so compile keys stay log-bounded. ``d_small``/``g_small``
    are fixed at 16 (the measured sweet spot: experiments/map_kernel_exp.py).
    """
    small_cap = 16
    is_small = (det_counts <= small_cap) & (gt_counts <= small_cap)
    big_idx = np.nonzero(~is_small)
    big_pairs = list(zip(big_idx[0].tolist(), big_idx[1].tolist()))
    if big_pairs:
        d_big = _next_pow2(int(min(max(det_counts[~is_small].max(), 1), max_det)))
        g_big = _next_pow2(int(max(gt_counts[~is_small].max(), 1)))
        d_big = max(d_big, 1)
    else:
        d_big, g_big = 1, 1
    return is_small, big_pairs, d_big, g_big
