"""Retrieval precision functional (reference: functional/retrieval/precision.py:20-70)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_precision(preds: Array, target: Array, top_k: Optional[int] = None, adaptive_k: bool = False) -> Array:
    """Precision@k for a single query."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    if top_k is None or (adaptive_k and top_k > preds.shape[-1]):
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    relevant = (ranked_targets(preds, target)[: min(top_k, preds.shape[-1])] > 0).sum().astype(jnp.float32)
    return jnp.where(target.sum() > 0, relevant / top_k, 0.0)
