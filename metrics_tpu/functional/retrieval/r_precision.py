"""Retrieval R-precision functional (reference: functional/retrieval/r_precision.py:20-55).

jit note: the cutoff k equals the (data-dependent) number of relevant docs; expressed
as a rank mask instead of a slice so the kernel stays static-shape.
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_r_precision(preds: Array, target: Array) -> Array:
    """R-precision for a single query."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    n_rel = (target > 0).sum()
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    t = (ranked_targets(preds, target) > 0).astype(jnp.float32)
    rank = jnp.arange(1, preds.shape[-1] + 1)
    rel_in_r = jnp.where(rank <= n_rel, t, 0.0).sum()
    return jnp.where(n_rel > 0, rel_in_r / jnp.maximum(n_rel.astype(jnp.float32), 1.0), 0.0)
