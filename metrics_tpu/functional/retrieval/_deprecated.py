"""Root-functional deprecation shims (reference: functional/retrieval/_deprecated.py).

``metrics_tpu.functional.<name>`` warns; ``metrics_tpu.functional.retrieval.<name>``
stays silent (reference utilities/prints.py:67-72).
"""
from metrics_tpu.functional.retrieval import retrieval_average_precision, retrieval_fall_out, retrieval_hit_rate, retrieval_normalized_dcg, retrieval_precision, retrieval_precision_recall_curve, retrieval_r_precision, retrieval_recall, retrieval_reciprocal_rank
from metrics_tpu.utils.prints import _root_func_shim

_retrieval_average_precision = _root_func_shim(retrieval_average_precision, "retrieval_average_precision", "retrieval")
_retrieval_fall_out = _root_func_shim(retrieval_fall_out, "retrieval_fall_out", "retrieval")
_retrieval_hit_rate = _root_func_shim(retrieval_hit_rate, "retrieval_hit_rate", "retrieval")
_retrieval_normalized_dcg = _root_func_shim(retrieval_normalized_dcg, "retrieval_normalized_dcg", "retrieval")
_retrieval_precision = _root_func_shim(retrieval_precision, "retrieval_precision", "retrieval")
_retrieval_precision_recall_curve = _root_func_shim(retrieval_precision_recall_curve, "retrieval_precision_recall_curve", "retrieval")
_retrieval_r_precision = _root_func_shim(retrieval_r_precision, "retrieval_r_precision", "retrieval")
_retrieval_recall = _root_func_shim(retrieval_recall, "retrieval_recall", "retrieval")
_retrieval_reciprocal_rank = _root_func_shim(retrieval_reciprocal_rank, "retrieval_reciprocal_rank", "retrieval")

__all__ = ["_retrieval_average_precision", "_retrieval_fall_out", "_retrieval_hit_rate", "_retrieval_normalized_dcg", "_retrieval_precision", "_retrieval_precision_recall_curve", "_retrieval_r_precision", "_retrieval_recall", "_retrieval_reciprocal_rank"]
