"""Retrieval MRR functional (reference: functional/retrieval/reciprocal_rank.py:20-56)."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_reciprocal_rank(preds: Array, target: Array) -> Array:
    """Reciprocal rank of the first relevant document for a single query.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.retrieval import retrieval_reciprocal_rank
        >>> preds = jnp.array([0.2, 0.3, 0.5])
        >>> target = jnp.array([False, True, False])
        >>> retrieval_reciprocal_rank(preds, target)
        Array(0.5, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    t = ranked_targets(preds, target) > 0
    rank = jnp.arange(1, preds.shape[-1] + 1)
    first = jnp.min(jnp.where(t, rank, preds.shape[-1] + 1))
    return jnp.where(t.any(), 1.0 / first.astype(jnp.float32), 0.0)
