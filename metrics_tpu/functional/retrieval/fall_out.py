"""Retrieval fall-out functional (reference: functional/retrieval/fall_out.py:20-66)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_fall_out(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Fall-out@k for a single query: non-relevant retrieved / all non-relevant."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    neg = 1 - (target > 0).astype(jnp.int32)
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    nonrel_in_k = (1 - (ranked_targets(preds, target)[:top_k] > 0).astype(jnp.int32)).sum().astype(jnp.float32)
    total_neg = neg.sum().astype(jnp.float32)
    return jnp.where(total_neg > 0, nonrel_in_k / jnp.maximum(total_neg, 1.0), 0.0)
