"""Retrieval recall functional (reference: functional/retrieval/recall.py:20-66)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Recall@k for a single query."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    order = jnp.argsort(-preds)
    relevant = (target[order][:top_k] > 0).sum().astype(jnp.float32)
    total = (target > 0).sum().astype(jnp.float32)
    return jnp.where(total > 0, relevant / jnp.maximum(total, 1.0), 0.0)
