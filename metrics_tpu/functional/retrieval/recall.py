"""Retrieval recall functional (reference: functional/retrieval/recall.py:20-66)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_recall(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """Recall@k for a single query."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    # payload sort, not argsort+gather: ops/segment.py's measured ~90 ms/16M-row
    # gather trap applies to every vmapped batch of these functionals
    relevant = (ranked_targets(preds, target)[:top_k] > 0).sum().astype(jnp.float32)
    total = (target > 0).sum().astype(jnp.float32)
    return jnp.where(total > 0, relevant / jnp.maximum(total, 1.0), 0.0)
