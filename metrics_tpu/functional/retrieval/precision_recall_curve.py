"""Retrieval precision-recall curve functional (reference: functional/retrieval/precision_recall_curve.py:24-99)."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs
from metrics_tpu.utils.data import _cumsum


def retrieval_precision_recall_curve(
    preds: Array, target: Array, max_k: Optional[int] = None, adaptive_k: bool = False
) -> Tuple[Array, Array, Array]:
    """Precision/recall at every cutoff k = 1..max_k for a single query.

    Args:
        preds: document relevance scores.
        target: binary relevance labels.
        max_k: largest cutoff (default: number of documents).
        adaptive_k: clamp per-position denominators at the document count when
            ``max_k`` exceeds it.

    Example:
        >>> import jax.numpy as jnp
        >>> preds = jnp.array([0.2, 0.3, 0.5])
        >>> target = jnp.array([True, False, True])
        >>> precisions, recalls, top_k = retrieval_precision_recall_curve(preds, target, max_k=2)
        >>> precisions
        Array([1. , 0.5], dtype=float32)
        >>> recalls
        Array([0.5, 0.5], dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if not isinstance(adaptive_k, bool):
        raise ValueError("`adaptive_k` has to be a boolean")
    n_docs = preds.shape[-1]
    if max_k is None:
        max_k = n_docs
    if not (isinstance(max_k, int) and max_k > 0):
        raise ValueError("`max_k` has to be a positive integer or None")

    if adaptive_k and max_k > n_docs:
        topk = jnp.concatenate([jnp.arange(1, n_docs + 1), jnp.full((max_k - n_docs,), n_docs)])
    else:
        topk = jnp.arange(1, max_k + 1)

    k_eff = min(max_k, n_docs)
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    relevant = ranked_targets(preds, target)[:k_eff].astype(jnp.float32)
    relevant = jnp.pad(relevant, (0, max(0, max_k - k_eff)))
    relevant = _cumsum(relevant, axis=0)

    n_pos = target.sum()
    recall = jnp.where(n_pos > 0, relevant / jnp.maximum(n_pos, 1), 0.0)
    precision = jnp.where(n_pos > 0, relevant / topk, 0.0)
    return precision.astype(jnp.float32), recall.astype(jnp.float32), topk
