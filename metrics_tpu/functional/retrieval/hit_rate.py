"""Retrieval hit rate functional (reference: functional/retrieval/hit_rate.py:20-62)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_hit_rate(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """HitRate@k for a single query."""
    preds, target = _check_retrieval_functional_inputs(preds, target)
    if top_k is None:
        top_k = preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    relevant = (ranked_targets(preds, target)[:top_k] > 0).sum()
    return (relevant > 0).astype(jnp.float32)
