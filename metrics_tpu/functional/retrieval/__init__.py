from metrics_tpu.functional.retrieval.average_precision import retrieval_average_precision
from metrics_tpu.functional.retrieval.fall_out import retrieval_fall_out
from metrics_tpu.functional.retrieval.hit_rate import retrieval_hit_rate
from metrics_tpu.functional.retrieval.ndcg import retrieval_normalized_dcg
from metrics_tpu.functional.retrieval.precision import retrieval_precision
from metrics_tpu.functional.retrieval.precision_recall_curve import retrieval_precision_recall_curve
from metrics_tpu.functional.retrieval.r_precision import retrieval_r_precision
from metrics_tpu.functional.retrieval.recall import retrieval_recall
from metrics_tpu.functional.retrieval.reciprocal_rank import retrieval_reciprocal_rank

__all__ = [
    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_precision_recall_curve",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",
]
