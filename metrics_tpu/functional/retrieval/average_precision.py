"""Retrieval AP functional (reference: functional/retrieval/average_precision.py:20-60)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def retrieval_average_precision(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """AP for a single query.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.retrieval import retrieval_average_precision
        >>> preds = jnp.array([0.2, 0.3, 0.5])
        >>> target = jnp.array([True, False, True])
        >>> retrieval_average_precision(preds, target)
        Array(0.8333334, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target)
    top_k = top_k or preds.shape[-1]
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError(f"Argument ``top_k`` has to be a positive integer or None, but got {top_k}.")
    k = min(top_k, preds.shape[-1])
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    t = (ranked_targets(preds, target)[:k] > 0).astype(jnp.float32)
    n_rel = t.sum()
    pos = jnp.arange(1, k + 1, dtype=jnp.float32)
    cumrel = jnp.cumsum(t)
    return jnp.where(n_rel > 0, (t * cumrel / pos).sum() / jnp.maximum(n_rel, 1.0), 0.0)
