"""Retrieval NDCG functional (reference: functional/retrieval/ndcg.py:20-70)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.rank import ranked_targets
from metrics_tpu.utils.checks import _check_retrieval_functional_inputs


def _dcg(target: Array) -> Array:
    denom = jnp.log2(jnp.arange(target.shape[-1], dtype=jnp.float32) + 2.0)
    return (target / denom).sum(axis=-1)


def retrieval_normalized_dcg(preds: Array, target: Array, top_k: Optional[int] = None) -> Array:
    """NDCG@k for a single query (graded relevance allowed).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.retrieval import retrieval_normalized_dcg
        >>> preds = jnp.array([.1, .2, .3, 4, 70.])
        >>> target = jnp.array([10, 0, 0, 1, 5])
        >>> retrieval_normalized_dcg(preds, target)
        Array(0.6956941, dtype=float32)
    """
    preds, target = _check_retrieval_functional_inputs(preds, target, allow_non_binary_target=True)
    top_k = preds.shape[-1] if top_k is None else top_k
    if not (isinstance(top_k, int) and top_k > 0):
        raise ValueError("`top_k` has to be a positive integer or None")
    target = target.astype(jnp.float32)
    # payload sort, not argsort+gather (ops/segment.py gather-trap notes)
    sorted_target = ranked_targets(preds, target)[:top_k]
    ideal_target = -jnp.sort(-target)[:top_k]
    ideal_dcg = _dcg(ideal_target)
    target_dcg = _dcg(sorted_target)
    score = jnp.where(ideal_dcg > 0, target_dcg / jnp.maximum(ideal_dcg, 1e-12), 0.0)
    return jnp.clip(score, 0.0, 1.0)
