"""Root-functional deprecation shims (reference: functional/audio/_deprecated.py).

``metrics_tpu.functional.<name>`` warns; ``metrics_tpu.functional.audio.<name>``
stays silent (reference utilities/prints.py:67-72).
"""
from metrics_tpu.functional.audio import permutation_invariant_training, pit_permutate, scale_invariant_signal_distortion_ratio, scale_invariant_signal_noise_ratio, signal_distortion_ratio, signal_noise_ratio
from metrics_tpu.utils.prints import _root_func_shim

_permutation_invariant_training = _root_func_shim(permutation_invariant_training, "permutation_invariant_training", "audio")
_pit_permutate = _root_func_shim(pit_permutate, "pit_permutate", "audio")
_scale_invariant_signal_distortion_ratio = _root_func_shim(scale_invariant_signal_distortion_ratio, "scale_invariant_signal_distortion_ratio", "audio")
_scale_invariant_signal_noise_ratio = _root_func_shim(scale_invariant_signal_noise_ratio, "scale_invariant_signal_noise_ratio", "audio")
_signal_distortion_ratio = _root_func_shim(signal_distortion_ratio, "signal_distortion_ratio", "audio")
_signal_noise_ratio = _root_func_shim(signal_noise_ratio, "signal_noise_ratio", "audio")

__all__ = ["_permutation_invariant_training", "_pit_permutate", "_scale_invariant_signal_distortion_ratio", "_scale_invariant_signal_noise_ratio", "_signal_distortion_ratio", "_signal_noise_ratio"]
