"""PESQ functional (reference: functional/audio/pesq.py).

PESQ (ITU-T P.862) is a ~1500-line standardized C reference covering level/time
alignment, an auditory transform, and a cognitive model; like the reference
library, this function delegates to the ``pesq`` wheel (the reference raises the
same ``ModuleNotFoundError`` when the wheel is absent — functional/audio/pesq.py:30).

Round-5 assessment of an in-repo port (the STOI treatment, functional/audio/stoi.py):
evaluated and deliberately declined. Unlike STOI — whose published paper specifies
the complete algorithm — P.862 conformance hinges on large numeric tables (Bark band
edges and widths, absolute-hearing-threshold and loudness-scaling curves per band,
IRS filter coefficients) that exist only in the ITU's source distribution, not in
the paper; and this environment carries neither that source nor the ``pesq`` wheel,
so a port could not be validated against ANY oracle (the stated acceptance bar,
MOS-LQO within ~1e-4 of the wheel, is unmeasurable here). A "P.862-shaped" pipeline
with reinvented constants would return plausible-looking but non-comparable MOS
values — strictly worse than failing fast with parity-identical behavior to the
reference. Revisit if the ITU reference tables or the wheel become available for
conformance testing.
"""
from typing import Union

import numpy as np
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.imports import _PESQ_AVAILABLE

__doctest_requires__ = {("perceptual_evaluation_speech_quality",): ["pesq"]}


def perceptual_evaluation_speech_quality(
    preds: Union[Array, np.ndarray],
    target: Union[Array, np.ndarray],
    fs: int,
    mode: str,
    keep_same_device: bool = False,
    n_processes: int = 1,
) -> Array:
    """PESQ MOS-LQO score (requires the ``pesq`` package).

    Args:
        preds: degraded signal ``(..., time)``.
        target: clean reference signal ``(..., time)``.
        fs: sampling rate — 8000 (nb) or 16000 (wb only).
        mode: ``"wb"`` (wide-band) or ``"nb"`` (narrow-band).
        keep_same_device: accepted for reference API parity (no-op).
        n_processes: parallel workers for batched evaluation.
    """
    if not _PESQ_AVAILABLE:
        raise ModuleNotFoundError(
            "PESQ metric requires that `pesq` is installed. Either install as `pip install pesq`, or use the "
            "host environment that bundles it. A from-scratch port is not provided because only the ITU "
            "reference implementation produces comparable MOS-LQO values."
        )
    import pesq as pesq_backend

    if mode not in ("wb", "nb"):
        raise ValueError(f"Expected argument `mode` to either be 'wb' or 'nb' but got {mode}")
    if fs not in (8000, 16000):
        raise ValueError(f"Expected argument `fs` to either be 8000 or 16000 but got {fs}")
    if fs == 8000 and mode == "wb":
        raise ValueError("Expected argument `mode` to be 'nb' when `fs=8000`")

    preds_np = np.asarray(preds, dtype=np.float32)
    target_np = np.asarray(target, dtype=np.float32)
    if preds_np.shape != target_np.shape:
        raise RuntimeError("Predictions and targets are expected to have the same shape")

    if preds_np.ndim == 1:
        out = np.array(pesq_backend.pesq(fs, target_np, preds_np, mode), np.float32)
    else:
        flat_p = preds_np.reshape(-1, preds_np.shape[-1])
        flat_t = target_np.reshape(-1, target_np.shape[-1])
        if n_processes > 1:
            vals = pesq_backend.pesq_batch(fs, flat_t, flat_p, mode, n_processor=n_processes)
            out = np.array(vals, np.float32).reshape(preds_np.shape[:-1])
        else:
            vals = [pesq_backend.pesq(fs, t, p, mode) for p, t in zip(flat_p, flat_t)]
            out = np.array(vals, np.float32).reshape(preds_np.shape[:-1])
    return jnp.asarray(out)
