"""SNR / SI-SNR functionals (reference: functional/audio/snr.py:20-120).

Pure-jnp, fully jit/grad/vmap/shard_map-safe.
"""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def signal_noise_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Signal-to-noise ratio in dB, per sample over the trailing time axis.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> signal_noise_ratio(preds, target)
        Array(16.18..., dtype=float32)
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    noise = target - preds
    snr_value = (jnp.sum(target**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(snr_value)


def scale_invariant_signal_noise_ratio(preds: Array, target: Array) -> Array:
    """Scale-invariant SNR in dB (equals SI-SDR with zero-mean inputs).

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_noise_ratio(preds, target)
        Array(15.09..., dtype=float32)
    """
    from metrics_tpu.functional.audio.sdr import scale_invariant_signal_distortion_ratio

    return scale_invariant_signal_distortion_ratio(preds=preds, target=target, zero_mean=True)
