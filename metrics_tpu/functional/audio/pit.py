"""Permutation-invariant training functionals (reference: functional/audio/pit.py:29-200).

TPU redesign: permutation scoring is fully vectorized — one gather + mean over a
static ``(spk!, spk)`` permutation table instead of the reference's per-permutation
loop, so the whole path jits (the pairwise metric matrix itself is still built with
``spk×spk`` traced calls of the user metric, which XLA fuses). The scipy
linear-sum-assignment route (host-side) kicks in for ``spk_num > 8`` where ``spk!``
blows up (the reference switches at 3; exhaustive up to 8 ≈ 40k permutations is a
trivial on-device gather and avoids the host round-trip).
"""
from itertools import permutations
from typing import Any, Callable, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.imports import _SCIPY_AVAILABLE

_EXHAUSTIVE_SPK_LIMIT = 8

# permutation tables keyed by speaker count — cached as HOST numpy: a jnp array
# built during a jit trace is a tracer, and caching it leaks the tracer into
# later traces (UnexpectedTracerError; caught by the jit-safety contract sweep)
_ps_cache: dict = {}


def _perm_table(spk_num: int) -> jnp.ndarray:
    """All permutations as an ``(spk!, spk)`` int array (host-cached)."""
    if spk_num not in _ps_cache:
        _ps_cache[spk_num] = np.asarray(list(permutations(range(spk_num))), np.int32)
    return jnp.asarray(_ps_cache[spk_num])


def _find_best_perm_by_exhaustive_method(metric_mtx: Array, larger_is_better: bool) -> Tuple[Array, Array]:
    """Best permutation by scoring every permutation — one gather, jit-safe.

    ``metric_mtx[b, t, p]`` is the metric of prediction ``p`` against target ``t``.
    """
    spk_num = metric_mtx.shape[-1]
    ps = _perm_table(spk_num)  # [perm_num, spk]
    # score[b, k] = mean over targets t of metric_mtx[b, t, ps[k, t]]
    scores = jnp.mean(jnp.take_along_axis(metric_mtx[:, None, :, :], ps[None, :, :, None], axis=-1)[..., 0], axis=-1)
    best_indexes = jnp.argmax(scores, axis=-1) if larger_is_better else jnp.argmin(scores, axis=-1)
    best_metric = jnp.take_along_axis(scores, best_indexes[:, None], axis=-1)[:, 0]
    best_perm = ps[best_indexes]
    return best_metric, best_perm


def _find_best_perm_by_linear_sum_assignment(metric_mtx: Array, larger_is_better: bool) -> Tuple[Array, Array]:
    """Hungarian assignment on host (scipy) for very large speaker counts."""
    from scipy.optimize import linear_sum_assignment

    mtx = np.asarray(metric_mtx)
    best_perm = jnp.asarray(
        [linear_sum_assignment(pwm, maximize=larger_is_better)[1] for pwm in mtx], jnp.int32
    )
    best_metric = jnp.mean(jnp.take_along_axis(metric_mtx, best_perm[:, :, None], axis=2), axis=(-1, -2))
    return best_metric, best_perm


def permutation_invariant_training(
    preds: Array,
    target: Array,
    metric_func: Callable,
    eval_func: str = "max",
    **kwargs: Any,
) -> Tuple[Array, Array]:
    """Permutation-invariant training metric for multi-talker separation.

    Args:
        preds: estimates ``(batch, spk, ...)``.
        target: references ``(batch, spk, ...)``.
        metric_func: pairwise metric ``f(preds[:, i], target[:, j]) -> (batch,)``.
        eval_func: ``"max"`` (higher better) or ``"min"``.
        kwargs: forwarded to ``metric_func``.

    Returns:
        ``(best_metric [batch], best_perm [batch, spk])`` where ``best_perm[b, t]``
        is the prediction index assigned to target ``t``.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.audio import scale_invariant_signal_distortion_ratio
        >>> preds = jnp.array([[[-0.0579, 0.3560, -0.9604], [-0.1719, 0.3205, 0.2951]]])
        >>> target = jnp.array([[[1.0958, -0.1648, 0.5228], [-0.4100, 1.1942, -0.5103]]])
        >>> best_metric, best_perm = permutation_invariant_training(
        ...     preds, target, scale_invariant_signal_distortion_ratio, 'max')
        >>> best_perm
        Array([[0, 1]], dtype=int32)
    """
    if preds.shape[0:2] != target.shape[0:2]:
        raise RuntimeError(
            "Predictions and targets are expected to have the same shape at the batch and speaker dimensions"
        )
    if eval_func not in ["max", "min"]:
        raise ValueError(f'eval_func can only be "max" or "min" but got {eval_func}')
    if target.ndim < 2:
        raise ValueError(f"Inputs must be of shape [batch, spk, ...], got {target.shape} and {preds.shape} instead")

    spk_num = target.shape[1]
    # metric matrix [batch, target_idx, preds_idx] via broadcast over flattened pairs
    rows = []
    for target_idx in range(spk_num):
        cols = [
            metric_func(preds[:, preds_idx, ...], target[:, target_idx, ...], **kwargs)
            for preds_idx in range(spk_num)
        ]
        rows.append(jnp.stack(cols, axis=-1))
    metric_mtx = jnp.stack(rows, axis=-2)  # [batch, spk, spk]

    larger_is_better = eval_func == "max"
    if spk_num <= _EXHAUSTIVE_SPK_LIMIT:
        return _find_best_perm_by_exhaustive_method(metric_mtx, larger_is_better)
    if not _SCIPY_AVAILABLE:
        # spk! permutation table would be astronomically large; Hungarian needs scipy
        raise ModuleNotFoundError(
            f"permutation_invariant_training with {spk_num} > {_EXHAUSTIVE_SPK_LIMIT} speakers requires `scipy` "
            "for the linear-sum-assignment solver. Install it with `pip install scipy`."
        )
    return _find_best_perm_by_linear_sum_assignment(metric_mtx, larger_is_better)


def pit_permutate(preds: Array, perm: Array) -> Array:
    """Reorder ``preds[b, spk, ...]`` according to ``perm[b, spk]``."""
    return jnp.take_along_axis(preds, perm.reshape(perm.shape + (1,) * (preds.ndim - 2)), axis=1)
