"""Short-Time Objective Intelligibility functional (reference: functional/audio/stoi.py).

The reference delegates to the ``pystoi`` wheel; this is a from-scratch NumPy port
of the published algorithm (Taal, Hendriks, Heusdens, Jensen, "An Algorithm for
Intelligibility Prediction of Time-Frequency Weighted Noisy Speech", 2011):

1. resample both signals to 10 kHz,
2. remove frames more than 40 dB below the loudest frame (256-sample hann frames,
   50% overlap, overlap-add reconstruction),
3. 512-point STFT (256-sample frames, 128 hop) -> 15 one-third-octave bands from
   150 Hz,
4. per 30-frame segment and band: scale the degraded segment to the clean energy,
   clip at -15 dB SDR, and correlate with the clean segment; average everything.

Host-side by nature (silent-frame removal is data-dependent-shape). When the
``pystoi`` wheel is installed it is used instead for bit-exact community parity;
this port is the offline default. Delta vs pystoi: the extended-STOI
normalization omits pystoi's random dithering noise (deterministic eps guards
instead).
"""
import functools
from typing import Union

import numpy as np
from jax import Array
import jax.numpy as jnp

from metrics_tpu.utils.imports import _PYSTOI_AVAILABLE, _SCIPY_AVAILABLE
from metrics_tpu.utils.prints import rank_zero_warn

FS = 10000  # target sample rate
N_FRAME = 256  # silence-removal / STFT frame
NFFT = 512
NUMBAND = 15
MINFREQ = 150
N_SEG = 30  # frames per intelligibility segment
BETA = -15.0  # lower SDR clip bound (dB)
DYN_RANGE = 40.0
_EPS = np.finfo(np.float64).eps


@functools.lru_cache(maxsize=8)
def _thirdoct(fs: int, nfft: int, num_bands: int, min_freq: int) -> np.ndarray:
    """One-third-octave band matrix over rfft bins (published design)."""
    f = np.linspace(0, fs, nfft + 1)[: nfft // 2 + 1]
    k = np.arange(num_bands, dtype=np.float64)
    freq_low = min_freq * np.power(2.0, (2 * k - 1) / 6)
    freq_high = min_freq * np.power(2.0, (2 * k + 1) / 6)
    obm = np.zeros((num_bands, len(f)))
    for i in range(num_bands):
        fl_bin = int(np.argmin(np.square(f - freq_low[i])))
        fh_bin = int(np.argmin(np.square(f - freq_high[i])))
        obm[i, fl_bin:fh_bin] = 1
    return obm


def _hann(framelen: int) -> np.ndarray:
    return np.hanning(framelen + 2)[1:-1]


def _frame(x: np.ndarray, framelen: int, hop: int) -> np.ndarray:
    starts = range(0, len(x) - framelen, hop)
    return np.array([x[i : i + framelen] for i in starts])


def _remove_silent_frames(x: np.ndarray, y: np.ndarray, dyn_range: float, framelen: int, hop: int):
    w = _hann(framelen)
    x_frames = _frame(x, framelen, hop) * w
    y_frames = _frame(y, framelen, hop) * w
    energies = 20 * np.log10(np.linalg.norm(x_frames, axis=1) + _EPS)
    mask = (np.max(energies) - dyn_range - energies) < 0
    x_frames, y_frames = x_frames[mask], y_frames[mask]
    if len(x_frames) == 0:
        return np.zeros(0), np.zeros(0)
    n_sil = (len(x_frames) - 1) * hop + framelen
    x_sil = np.zeros(n_sil)
    y_sil = np.zeros(n_sil)
    for i in range(len(x_frames)):
        x_sil[i * hop : i * hop + framelen] += x_frames[i]
        y_sil[i * hop : i * hop + framelen] += y_frames[i]
    return x_sil, y_sil


def _stft_bands(x: np.ndarray, obm: np.ndarray) -> np.ndarray:
    """(bands, frames) one-third-octave magnitudes."""
    w = _hann(N_FRAME)
    frames = _frame(x, N_FRAME, N_FRAME // 2) * w
    spec = np.fft.rfft(frames, n=NFFT, axis=-1)  # (frames, bins)
    return np.sqrt(obm @ np.square(np.abs(spec)).T)  # (bands, frames)


def _segments(tob: np.ndarray, n: int) -> np.ndarray:
    """(num_segments, bands, n) sliding segments of n frames."""
    return np.array([tob[:, m - n : m] for m in range(n, tob.shape[1] + 1)])


def _stoi_numpy(clean: np.ndarray, degraded: np.ndarray, fs: int, extended: bool) -> float:
    if clean.shape != degraded.shape:
        raise ValueError("Clean and degraded signals must have the same shape")
    if fs != FS:
        if not _SCIPY_AVAILABLE:
            raise ModuleNotFoundError("Resampling to 10 kHz requires scipy.")
        from scipy.signal import resample_poly

        clean = resample_poly(clean, FS, fs)
        degraded = resample_poly(degraded, FS, fs)

    if len(clean) <= N_FRAME:
        rank_zero_warn(
            f"Signal too short for STOI ({len(clean)} <= {N_FRAME} samples at 10 kHz); returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5
    clean, degraded = _remove_silent_frames(clean, degraded, DYN_RANGE, N_FRAME, N_FRAME // 2)
    if len(clean) < N_FRAME + 1:
        # pystoi-compatible degenerate-input behavior: warn + sentinel, not crash
        rank_zero_warn("Not enough non-silent frames to compute STOI; returning 1e-5.", RuntimeWarning)
        return 1e-5

    obm = _thirdoct(FS, NFFT, NUMBAND, MINFREQ)
    x_tob = _stft_bands(clean, obm)
    y_tob = _stft_bands(degraded, obm)
    if x_tob.shape[1] < N_SEG:
        rank_zero_warn(
            f"Signal too short after silence removal ({x_tob.shape[1]} < {N_SEG} frames); returning 1e-5.",
            RuntimeWarning,
        )
        return 1e-5

    x_seg = _segments(x_tob, N_SEG)  # (M, bands, N)
    y_seg = _segments(y_tob, N_SEG)

    if extended:
        # row/col normalize deterministically, then mean correlation
        def _row_col_normalize(seg: np.ndarray) -> np.ndarray:
            seg = seg - np.mean(seg, axis=2, keepdims=True)
            seg = seg / (np.linalg.norm(seg, axis=2, keepdims=True) + _EPS)
            seg = seg - np.mean(seg, axis=1, keepdims=True)
            return seg / (np.linalg.norm(seg, axis=1, keepdims=True) + _EPS)

        x_n = _row_col_normalize(x_seg)
        y_n = _row_col_normalize(y_seg)
        return float(np.sum(x_n * y_n / N_SEG) / x_n.shape[0])

    norm_const = np.linalg.norm(x_seg, axis=2, keepdims=True) / (
        np.linalg.norm(y_seg, axis=2, keepdims=True) + _EPS
    )
    y_prim = np.minimum(y_seg * norm_const, x_seg * (1 + np.power(10.0, -BETA / 20)))

    y_prim = y_prim - np.mean(y_prim, axis=2, keepdims=True)
    x_cent = x_seg - np.mean(x_seg, axis=2, keepdims=True)
    y_prim = y_prim / (np.linalg.norm(y_prim, axis=2, keepdims=True) + _EPS)
    x_cent = x_cent / (np.linalg.norm(x_cent, axis=2, keepdims=True) + _EPS)
    correlations = np.sum(y_prim * x_cent, axis=2)  # (M, bands)
    return float(np.mean(correlations))


def short_time_objective_intelligibility(
    preds: Union[Array, np.ndarray],
    target: Union[Array, np.ndarray],
    fs: int,
    extended: bool = False,
    keep_same_device: bool = False,
) -> Array:
    """STOI intelligibility score in ~[0, 1] (higher = more intelligible).

    Args:
        preds: degraded signal ``(..., time)``.
        target: clean reference signal ``(..., time)``.
        fs: sampling rate of the signals in Hz.
        extended: compute extended STOI (language-independent variant).
        keep_same_device: accepted for reference API parity (a no-op: the result
            is always a host-backed jnp scalar array).
    """
    preds_np = np.asarray(preds, dtype=np.float64)
    target_np = np.asarray(target, dtype=np.float64)
    if preds_np.shape != target_np.shape:
        raise RuntimeError("Predictions and targets are expected to have the same shape")
    flat_p = preds_np.reshape(-1, preds_np.shape[-1])
    flat_t = target_np.reshape(-1, target_np.shape[-1])
    if _PYSTOI_AVAILABLE:
        from pystoi import stoi as _pystoi

        vals = [_pystoi(t, p, fs, extended=extended) for p, t in zip(flat_p, flat_t)]
    else:
        vals = [_stoi_numpy(t, p, fs, extended) for p, t in zip(flat_p, flat_t)]
    out = np.array(vals, dtype=np.float32).reshape(preds_np.shape[:-1])
    return jnp.asarray(out)
