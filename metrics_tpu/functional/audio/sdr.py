"""SDR / SI-SDR functionals (reference: functional/audio/sdr.py:36-240).

SDR solves for the optimal length-``filter_length`` distortion filter projecting
``preds`` onto the column space of shifted ``target``: FFT auto/cross-correlation,
then a symmetric-Toeplitz linear solve. Everything is jnp — the Toeplitz matrix is
built with a static gather (``|i-j|`` indexing) instead of the reference's strided
view, so the whole computation jits and batches with ``vmap``.

Precision note: the reference upcasts to float64; on TPU this implementation
follows the enabled jax precision (float32 unless ``jax_enable_x64``). With the
default 512-tap filter the f32 solve is within ~1e-3 dB of the f64 reference for
typical (non-degenerate) signals; enable x64 for bit-level parity on CPU.
"""
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.prints import rank_zero_warn


def _symmetric_toeplitz(vector: Array) -> Array:
    """Symmetric Toeplitz matrix ``M[..., i, j] = vector[..., |i - j|]``."""
    n = vector.shape[-1]
    idx = jnp.abs(jnp.arange(n)[:, None] - jnp.arange(n)[None, :])
    return vector[..., idx]


def _compute_autocorr_crosscorr(target: Array, preds: Array, corr_len: int):
    """FFT-based autocorrelation of ``target`` and cross-correlation with ``preds``."""
    n_fft = 2 ** math.ceil(math.log2(preds.shape[-1] + target.shape[-1] - 1))
    t_fft = jnp.fft.rfft(target, n=n_fft, axis=-1)
    r_0 = jnp.fft.irfft(t_fft.real**2 + t_fft.imag**2, n=n_fft)[..., :corr_len]
    p_fft = jnp.fft.rfft(preds, n=n_fft, axis=-1)
    b = jnp.fft.irfft(jnp.conj(t_fft) * p_fft, n=n_fft, axis=-1)[..., :corr_len]
    return r_0, b


def signal_distortion_ratio(
    preds: Array,
    target: Array,
    use_cg_iter: Optional[int] = None,
    filter_length: int = 512,
    zero_mean: bool = False,
    load_diag: Optional[float] = None,
) -> Array:
    """Signal-to-distortion ratio in dB, per sample over the trailing time axis.

    Args:
        preds: estimated signal ``(..., time)``.
        target: reference signal ``(..., time)``.
        use_cg_iter: accepted for API parity; the dense Toeplitz solve is already
            batched/jitted here, so the conjugate-gradient path is not used.
        filter_length: length of the allowed distortion filter.
        zero_mean: subtract signal means first.
        load_diag: diagonal loading to stabilize the solve for degenerate targets.
    """
    _check_same_shape(preds, target)
    # f64 if x64 enabled, else f32 — gate on the config (same idiom as
    # functional/pairwise/euclidean.py) instead of promote_types(.., float64),
    # which under the default config requests f64 and is silently truncated to
    # f32 with a per-trace UserWarning (tmsan TMS-F64 hygiene)
    wide = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    compute_dtype = jnp.promote_types(preds.dtype, wide)
    out_dtype = preds.dtype
    preds = preds.astype(compute_dtype)
    target = target.astype(compute_dtype)

    if use_cg_iter is not None:
        rank_zero_warn(
            "`use_cg_iter` is accepted for API parity but ignored: the dense Toeplitz solve is used.",
            UserWarning,
        )

    if zero_mean:
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
        target = target - jnp.mean(target, axis=-1, keepdims=True)

    target = target / jnp.clip(jnp.linalg.norm(target, axis=-1, keepdims=True), min=1e-6)
    preds = preds / jnp.clip(jnp.linalg.norm(preds, axis=-1, keepdims=True), min=1e-6)

    r_0, b = _compute_autocorr_crosscorr(target, preds, corr_len=filter_length)
    if load_diag is not None:
        r_0 = r_0.at[..., 0].add(load_diag)

    r = _symmetric_toeplitz(r_0)
    sol = jnp.linalg.solve(r, b[..., None])[..., 0]

    coh = jnp.einsum("...l,...l->...", b, sol)
    ratio = coh / (1 - coh)
    return (10.0 * jnp.log10(ratio)).astype(out_dtype)


def scale_invariant_signal_distortion_ratio(preds: Array, target: Array, zero_mean: bool = False) -> Array:
    """Scale-invariant SDR in dB, per sample over the trailing time axis.

    Example:
        >>> import jax.numpy as jnp
        >>> target = jnp.array([3.0, -0.5, 2.0, 7.0])
        >>> preds = jnp.array([2.5, 0.0, 2.0, 8.0])
        >>> scale_invariant_signal_distortion_ratio(preds, target)
        Array(18.40..., dtype=float32)
    """
    _check_same_shape(preds, target)
    eps = jnp.finfo(preds.dtype).eps
    if zero_mean:
        target = target - jnp.mean(target, axis=-1, keepdims=True)
        preds = preds - jnp.mean(preds, axis=-1, keepdims=True)
    alpha = (jnp.sum(preds * target, axis=-1, keepdims=True) + eps) / (
        jnp.sum(target**2, axis=-1, keepdims=True) + eps
    )
    target_scaled = alpha * target
    noise = target_scaled - preds
    val = (jnp.sum(target_scaled**2, axis=-1) + eps) / (jnp.sum(noise**2, axis=-1) + eps)
    return 10 * jnp.log10(val)
