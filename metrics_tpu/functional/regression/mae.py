"""MAE functional (reference: functional/regression/mae.py:22-70)."""
from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _mean_absolute_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    # dtype-preserving (tmsan TMS-UPCAST): bf16 inputs accumulate in bf16 so a
    # bf16-declared sum state is not silently promoted to f32
    preds = _as_float(preds)
    target = _as_float(target)
    return jnp.sum(jnp.abs(preds - target)), target.size


def _mean_absolute_error_compute(sum_abs_error: Array, n_obs: Union[int, Array]) -> Array:
    return sum_abs_error / n_obs


def mean_absolute_error(preds: Array, target: Array) -> Array:
    """Mean absolute error.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.regression import mean_absolute_error
        >>> x = jnp.array([0., 1, 2, 3])
        >>> y = jnp.array([0., 1, 2, 1])
        >>> mean_absolute_error(x, y)
        Array(0.5, dtype=float32)
    """
    sum_abs_error, n_obs = _mean_absolute_error_update(preds, target)
    return _mean_absolute_error_compute(sum_abs_error, n_obs)
