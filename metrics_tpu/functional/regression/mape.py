"""MAPE functional (reference: functional/regression/mape.py:22-88)."""
from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _mean_absolute_percentage_error_update(
    preds: Array, target: Array, epsilon: float = 1.17e-06
) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    target = _as_float(target)
    abs_per_error = jnp.abs(preds - target) / jnp.maximum(jnp.abs(target), epsilon)
    return jnp.sum(abs_per_error), target.size


def _mean_absolute_percentage_error_compute(sum_abs_per_error: Array, num_obs: Union[int, Array]) -> Array:
    return sum_abs_per_error / num_obs


def mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Mean absolute percentage error."""
    sum_abs_per_error, num_obs = _mean_absolute_percentage_error_update(preds, target)
    return _mean_absolute_percentage_error_compute(sum_abs_per_error, num_obs)
