"""Tweedie deviance functional (reference: functional/regression/tweedie_deviance.py:23-140).

jit note: the reference raises on invalid (preds, targets) domains per power; value
checks here run only on concrete inputs (skipped under tracing).
"""
from typing import Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape, _is_concrete
from metrics_tpu.utils.compute import _safe_xlogy


def _domain_check(preds: Array, targets: Array, power: float) -> None:
    if not _is_concrete(preds, targets):
        return
    p, t = np.asarray(preds), np.asarray(targets)
    if power == 1 and (np.any(p <= 0) or np.any(t < 0)):
        raise ValueError(f"For power={power}, 'preds' has to be strictly positive and 'targets' cannot be negative.")
    if power == 2 and (np.any(p <= 0) or np.any(t <= 0)):
        raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")
    if power < 0 and np.any(p <= 0):
        raise ValueError(f"For power={power}, 'preds' has to be strictly positive.")
    if 1 < power < 2 and (np.any(p <= 0) or np.any(t < 0)):
        raise ValueError(
            f"For power={power}, 'targets' has to be strictly positive and 'preds' cannot be negative."
        )
    if power > 2 and (np.any(p <= 0) or np.any(t <= 0)):
        raise ValueError(f"For power={power}, both 'preds' and 'targets' have to be strictly positive.")


def _tweedie_deviance_score_update(preds: Array, targets: Array, power: float = 0.0) -> Tuple[Array, Array]:
    _check_same_shape(preds, targets)
    if 0 < power < 1:
        raise ValueError(f"Deviance Score is not defined for power={power}.")
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    targets = _as_float(targets)

    if power == 0:
        deviance_score = (targets - preds) ** 2
    elif power == 1:
        _domain_check(preds, targets, power)
        deviance_score = 2 * (_safe_xlogy(targets, targets / preds) + preds - targets)
    elif power == 2:
        _domain_check(preds, targets, power)
        deviance_score = 2 * (jnp.log(preds / targets) + (targets / preds) - 1)
    else:
        _domain_check(preds, targets, power)
        term_1 = jnp.maximum(targets, 0.0) ** (2 - power) / ((1 - power) * (2 - power))
        term_2 = targets * preds ** (1 - power) / (1 - power)
        term_3 = preds ** (2 - power) / (2 - power)
        deviance_score = 2 * (term_1 - term_2 + term_3)

    return jnp.sum(deviance_score), jnp.asarray(deviance_score.size)


def _tweedie_deviance_score_compute(sum_deviance_score: Array, num_observations: Array) -> Array:
    return sum_deviance_score / num_observations


def tweedie_deviance_score(preds: Array, targets: Array, power: float = 0.0) -> Array:
    """Tweedie deviance score."""
    sum_deviance_score, num_observations = _tweedie_deviance_score_update(preds, targets, power)
    return _tweedie_deviance_score_compute(sum_deviance_score, num_observations)
