"""KL divergence functional (reference: functional/regression/kl_divergence.py:20-120)."""
from typing import Optional, Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.utils.compute import _safe_xlogy


def _kld_update(p: Array, q: Array, log_prob: bool) -> Tuple[Array, int]:
    _check_same_shape(p, q)
    if p.ndim != 2 or q.ndim != 2:
        raise ValueError(f"Expected both p and q distribution to be 2D but got {p.ndim} and {q.ndim} respectively")
    p = jnp.asarray(p, jnp.float32)
    q = jnp.asarray(q, jnp.float32)
    total = p.shape[0]
    if log_prob:
        measures = jnp.sum(jnp.exp(p) * (p - q), axis=-1)
    else:
        p = p / p.sum(axis=-1, keepdims=True)
        q = q / q.sum(axis=-1, keepdims=True)
        measures = _safe_xlogy(p, p / q).sum(axis=-1)
    return measures, total


def _kld_compute(measures: Array, total: Union[int, Array], reduction: Optional[str] = "mean") -> Array:
    if reduction == "sum":
        return measures.sum()
    if reduction == "mean":
        return measures.sum() / total
    if reduction is None or reduction == "none":
        return measures
    return measures / total


def kl_divergence(p: Array, q: Array, log_prob: bool = False, reduction: Optional[str] = "mean") -> Array:
    """KL divergence D(p||q) per sample with reduction."""
    measures, total = _kld_update(p, q, log_prob)
    return _kld_compute(measures, total, reduction)
