"""Spearman correlation functional (reference: functional/regression/spearman.py:22-120).

Ranking uses a fully-vectorized average-rank kernel (sort + segment means over ties)
instead of the reference's python loop over repeated values (:48-50) — O(n log n) on
device, jit-safe.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape
from metrics_tpu.functional.regression.pearson import _check_data_shape_to_num_outputs


def _rank_data(data: Array) -> Array:
    """Average ranks (ties share the mean of their positions), 1-indexed."""
    data = jnp.asarray(data)
    n = data.shape[0]
    order = jnp.argsort(data)
    sorted_vals = data[order]
    ranks_sorted = jnp.arange(1, n + 1, dtype=jnp.float32)
    # average rank over equal-value runs: segment ids by unique value
    is_new = jnp.concatenate([jnp.array([True]), sorted_vals[1:] != sorted_vals[:-1]])
    seg_ids = jnp.cumsum(is_new) - 1
    seg_sum = jnp.zeros(n, jnp.float32).at[seg_ids].add(ranks_sorted)
    seg_cnt = jnp.zeros(n, jnp.float32).at[seg_ids].add(1.0)
    avg_ranks_sorted = seg_sum[seg_ids] / seg_cnt[seg_ids]
    ranks = jnp.zeros(n, jnp.float32).at[order].set(avg_ranks_sorted)
    return ranks


def _spearman_corrcoef_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    if not (jnp.issubdtype(jnp.asarray(preds).dtype, jnp.floating) and jnp.issubdtype(jnp.asarray(target).dtype, jnp.floating)):
        raise TypeError(
            "Expected `preds` and `target` both to be floating point tensors, but got"
            f" {preds.dtype} and {target.dtype}"
        )
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    return jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)


def _spearman_corrcoef_compute(preds: Array, target: Array, eps: float = 1e-6) -> Array:
    """Reference: :77-104."""
    if preds.ndim == 1:
        preds = _rank_data(preds)
        target = _rank_data(target)
    else:
        preds = jnp.stack([_rank_data(preds[:, i]) for i in range(preds.shape[-1])], axis=-1)
        target = jnp.stack([_rank_data(target[:, i]) for i in range(target.shape[-1])], axis=-1)

    preds_diff = preds - preds.mean(0)
    target_diff = target - target.mean(0)
    cov = (preds_diff * target_diff).mean(0)
    preds_std = jnp.sqrt((preds_diff * preds_diff).mean(0))
    target_std = jnp.sqrt((target_diff * target_diff).mean(0))
    corrcoef = cov / (preds_std * target_std + eps)
    return jnp.clip(corrcoef, -1.0, 1.0)


def spearman_corrcoef(preds: Array, target: Array) -> Array:
    """Spearman rank correlation.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.regression import spearman_corrcoef
        >>> target = jnp.array([3., -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> spearman_corrcoef(preds, target)
        Array(0.9999992, dtype=float32)
    """
    preds, target = _spearman_corrcoef_update(preds, target, num_outputs=1 if preds.ndim == 1 else preds.shape[-1])
    return _spearman_corrcoef_compute(preds, target)
