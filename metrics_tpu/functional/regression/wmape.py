"""WMAPE functional (reference: functional/regression/wmape.py:22-83)."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _weighted_mean_absolute_percentage_error_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    target = _as_float(target)
    return jnp.sum(jnp.abs(preds - target)), jnp.sum(jnp.abs(target))


def _weighted_mean_absolute_percentage_error_compute(
    sum_abs_error: Array, sum_scale: Array, epsilon: float = 1.17e-06
) -> Array:
    return sum_abs_error / jnp.maximum(sum_scale, epsilon)


def weighted_mean_absolute_percentage_error(preds: Array, target: Array) -> Array:
    """Weighted mean absolute percentage error."""
    sum_abs_error, sum_scale = _weighted_mean_absolute_percentage_error_update(preds, target)
    return _weighted_mean_absolute_percentage_error_compute(sum_abs_error, sum_scale)
