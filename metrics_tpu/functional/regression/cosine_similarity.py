"""Cosine similarity functional (reference: functional/regression/cosine_similarity.py:22-90)."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _cosine_similarity_update(preds: Array, target: Array) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    return jnp.asarray(preds, jnp.float32), jnp.asarray(target, jnp.float32)


def _cosine_similarity_compute(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    dot_product = (preds * target).sum(axis=-1)
    preds_norm = jnp.linalg.norm(preds, axis=-1)
    target_norm = jnp.linalg.norm(target, axis=-1)
    similarity = dot_product / (preds_norm * target_norm)
    reduction_mapping = {
        "sum": jnp.sum,
        "mean": jnp.mean,
        "none": lambda x: x,
        None: lambda x: x,
    }
    return reduction_mapping[reduction](similarity)


def cosine_similarity(preds: Array, target: Array, reduction: Optional[str] = "sum") -> Array:
    """Cosine similarity between rows of preds and target."""
    preds, target = _cosine_similarity_update(preds, target)
    return _cosine_similarity_compute(preds, target, reduction)
