"""Minkowski distance functional (reference: functional/regression/minkowski.py:21-81)."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape
from metrics_tpu.utils.exceptions import MetricsUserError


def _minkowski_distance_update(preds: Array, targets: Array, p: float) -> Array:
    _check_same_shape(preds, targets)
    if not (isinstance(p, (float, int)) and p >= 1):
        raise MetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {p}")
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    targets = _as_float(targets)
    return jnp.sum(jnp.abs(preds - targets) ** p)


def _minkowski_distance_compute(distance: Array, p: float) -> Array:
    return distance ** (1.0 / p)


def minkowski_distance(preds: Array, targets: Array, p: float) -> Array:
    """Minkowski distance."""
    minkowski_dist_sum = _minkowski_distance_update(preds, targets, p)
    return _minkowski_distance_compute(minkowski_dist_sum, p)
