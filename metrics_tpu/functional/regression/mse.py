"""MSE functional (reference: functional/regression/mse.py:22-75)."""
from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _mean_squared_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    # dtype-preserving (tmsan TMS-UPCAST): bf16 inputs accumulate in bf16 so a
    # bf16-declared sum state is not silently promoted to f32
    preds = _as_float(preds)
    target = _as_float(target)
    diff = preds - target
    return jnp.sum(diff * diff), target.size


def _mean_squared_error_compute(sum_squared_error: Array, n_obs: Union[int, Array], squared: bool = True) -> Array:
    mse = sum_squared_error / n_obs
    return mse if squared else jnp.sqrt(mse)


def mean_squared_error(preds: Array, target: Array, squared: bool = True) -> Array:
    """Mean squared error (RMSE with ``squared=False``).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.regression import mean_squared_error
        >>> x = jnp.array([0., 1, 2, 3])
        >>> y = jnp.array([0., 1, 2, 2])
        >>> mean_squared_error(x, y)
        Array(0.25, dtype=float32)
    """
    sum_squared_error, n_obs = _mean_squared_error_update(preds, target)
    return _mean_squared_error_compute(sum_squared_error, n_obs, squared=squared)
