"""Kendall rank correlation functional (reference: functional/regression/kendall.py).

Variants tau-a/b/c. TPU-first design: O(n^2) pairwise concordance via broadcast
comparisons (sign outer products fused by XLA) — the reference's sort-based O(n log n)
path is host-bound; for metric-sized n the pairwise form vectorizes better and is
jit-safe. Optional alternative hypothesis t-test p-value as in the reference.
"""
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _kendall_stats_1d(preds: Array, target: Array, variant: str) -> Array:
    n = preds.shape[0]
    dx = jnp.sign(preds[:, None] - preds[None, :])
    dy = jnp.sign(target[:, None] - target[None, :])
    iu = jnp.triu_indices(n, k=1)
    dx = dx[iu]
    dy = dy[iu]
    con = jnp.sum((dx * dy) > 0)
    dis = jnp.sum((dx * dy) < 0)
    n_pairs = n * (n - 1) / 2
    if variant == "a":
        return (con - dis) / n_pairs
    ties_x = jnp.sum((dx == 0) & (dy != 0)) + jnp.sum((dx == 0) & (dy == 0))
    ties_y = jnp.sum((dy == 0) & (dx != 0)) + jnp.sum((dx == 0) & (dy == 0))
    if variant == "b":
        tx = jnp.sum(dx == 0)
        ty = jnp.sum(dy == 0)
        denom = jnp.sqrt((n_pairs - tx) * (n_pairs - ty))
        return (con - dis) / denom
    # variant c
    # m = min(number of unique values in x, y)
    ux = jnp.unique(preds, size=n, fill_value=jnp.inf)
    uy = jnp.unique(target, size=n, fill_value=jnp.inf)
    mx = jnp.sum(jnp.isfinite(ux))
    my = jnp.sum(jnp.isfinite(uy))
    m = jnp.minimum(mx, my)
    return 2 * (con - dis) / (n**2 * (m - 1) / m)


def kendall_rank_corrcoef(
    preds: Array,
    target: Array,
    variant: str = "b",
    t_test: bool = False,
    alternative: Optional[str] = "two-sided",
):
    """Kendall rank correlation (tau-a/b/c), optional p-value.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.regression import kendall_rank_corrcoef
        >>> target = jnp.array([3., -0.5, 2, 1])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> kendall_rank_corrcoef(preds, target)
        Array(0.33333334, dtype=float32)
    """
    if variant not in ("a", "b", "c"):
        raise ValueError(f"Argument `variant` is expected to be one of ('a', 'b', 'c'), but got {variant}")
    if t_test and alternative not in ("two-sided", "less", "greater"):
        raise ValueError(
            f"Argument `alternative` is expected to be one of ('two-sided', 'less', 'greater'), but got {alternative}"
        )
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)

    if preds.ndim == 1:
        tau = _kendall_stats_1d(preds, target, variant)
    else:
        tau = jnp.stack([_kendall_stats_1d(preds[:, i], target[:, i], variant) for i in range(preds.shape[-1])])

    tau = jnp.asarray(tau, jnp.float32)
    if not t_test:
        return tau

    # normal-approximation p-value (reference uses the same asymptotic form)
    n = preds.shape[0]
    var = (2 * (2 * n + 5)) / (9 * n * (n - 1))
    z = np.asarray(tau) / np.sqrt(var)
    from scipy.stats import norm

    if alternative == "two-sided":
        p = 2 * norm.sf(np.abs(z))
    elif alternative == "greater":
        p = norm.sf(z)
    else:
        p = norm.cdf(z)
    return tau, jnp.asarray(p, jnp.float32)
