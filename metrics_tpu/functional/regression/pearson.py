"""Pearson correlation functional (reference: functional/regression/pearson.py:22-140).

The running-update is the Welford-style parallel merge; multi-device sync stacks
per-device stats and `_final_aggregation` (regression/pearson.py:28-69) merges them —
this is the canonical custom-``dist_reduce_fx=None`` metric of the framework.
"""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape


def _check_data_shape_to_num_outputs(preds: Array, target: Array, num_outputs: int) -> None:
    if preds.ndim > 2 or target.ndim > 2:
        raise ValueError(
            f"Expected both predictions and target to be either 1- or 2-dimensional tensors,"
            f" but got {target.ndim} and {preds.ndim}."
        )
    # (N, 1) inputs count as single-output, matching the reference's condition
    # (functional/regression/utils.py:24: `preds.ndim == 1 or preds.shape[1] == 1`)
    cond1 = num_outputs == 1 and not (preds.ndim == 1 or preds.shape[1] == 1)
    cond2 = num_outputs > 1 and num_outputs != preds.shape[-1]
    if cond1 or cond2:
        raise ValueError(
            f"Expected argument `num_outputs` to match the second dimension of input, but got {num_outputs}"
            f" and {preds.shape[-1] if preds.ndim > 1 else 1}."
        )


def _pearson_corrcoef_update(
    preds: Array,
    target: Array,
    mean_x: Array,
    mean_y: Array,
    var_x: Array,
    var_y: Array,
    corr_xy: Array,
    n_prior: Array,
    num_outputs: int,
) -> Tuple[Array, Array, Array, Array, Array, Array]:
    """Running covariance update (reference: :22-70), branchless for jit."""
    _check_same_shape(preds, target)
    _check_data_shape_to_num_outputs(preds, target, num_outputs)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    cond = n_prior.mean() > 0
    n_obs = preds.shape[0]

    mx_new = jnp.where(cond, (n_prior * mean_x + preds.sum(0)) / (n_prior + n_obs), preds.mean(0))
    my_new = jnp.where(cond, (n_prior * mean_y + target.sum(0)) / (n_prior + n_obs), target.mean(0))
    n_prior = n_prior + n_obs

    var_x = var_x + jnp.where(
        cond,
        ((preds - mx_new) * (preds - mean_x)).sum(0),
        preds.var(0, ddof=1) * (n_obs - 1),
    )
    var_y = var_y + jnp.where(
        cond,
        ((target - my_new) * (target - mean_y)).sum(0),
        target.var(0, ddof=1) * (n_obs - 1),
    )
    corr_xy = corr_xy + ((preds - mx_new) * (target - mean_y)).sum(0)
    return mx_new, my_new, var_x, var_y, corr_xy, n_prior


def _pearson_corrcoef_compute(var_x: Array, var_y: Array, corr_xy: Array, nb: Array) -> Array:
    """Reference: :78-97."""
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    corr_xy = corr_xy / (nb - 1)
    corrcoef = jnp.squeeze(corr_xy / jnp.sqrt(var_x * var_y))
    return jnp.clip(corrcoef, -1.0, 1.0)


def pearson_corrcoef(preds: Array, target: Array) -> Array:
    """Pearson correlation coefficient.

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.regression import pearson_corrcoef
        >>> target = jnp.array([3., -0.5, 2, 7])
        >>> preds = jnp.array([2.5, 0.0, 2, 8])
        >>> pearson_corrcoef(preds, target)
        Array(0.98486954, dtype=float32)
    """
    d = preds.shape[1] if preds.ndim == 2 else 1
    _temp = jnp.zeros(d, dtype=jnp.float32)
    mean_x, mean_y, var_x = _temp, _temp.copy(), _temp.copy()
    var_y, corr_xy, nb = _temp.copy(), _temp.copy(), _temp.copy()
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, mean_x, mean_y, var_x, var_y, corr_xy, nb, num_outputs=d
    )
    return _pearson_corrcoef_compute(var_x, var_y, corr_xy, nb)
