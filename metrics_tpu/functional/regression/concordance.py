"""Concordance correlation functional (reference: functional/regression/concordance.py:20-80)."""
import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.regression.pearson import (
    _pearson_corrcoef_compute,
    _pearson_corrcoef_update,
)


def _concordance_corrcoef_compute(
    mean_x: Array, mean_y: Array, var_x: Array, var_y: Array, corr_xy: Array, nb: Array
) -> Array:
    """Reference: :20-31."""
    pearson = _pearson_corrcoef_compute(var_x.copy(), var_y.copy(), corr_xy.copy(), nb)
    var_x = var_x / (nb - 1)
    var_y = var_y / (nb - 1)
    return jnp.squeeze(2.0 * pearson * jnp.sqrt(var_x) * jnp.sqrt(var_y) / (var_x + var_y + (mean_x - mean_y) ** 2))


def concordance_corrcoef(preds: Array, target: Array) -> Array:
    """Concordance correlation coefficient."""
    d = preds.shape[1] if preds.ndim == 2 else 1
    z = jnp.zeros(d, dtype=jnp.float32)
    mean_x, mean_y, var_x, var_y, corr_xy, nb = _pearson_corrcoef_update(
        preds, target, z, z, z, z, z, z, num_outputs=d
    )
    return _concordance_corrcoef_compute(mean_x, mean_y, var_x, var_y, corr_xy, nb)
