"""MSLE functional (reference: functional/regression/log_mse.py:22-74)."""
from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _mean_squared_log_error_update(preds: Array, target: Array) -> Tuple[Array, int]:
    _check_same_shape(preds, target)
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    target = _as_float(target)
    return jnp.sum((jnp.log1p(preds) - jnp.log1p(target)) ** 2), target.size


def _mean_squared_log_error_compute(sum_squared_log_error: Array, n_obs: Union[int, Array]) -> Array:
    return sum_squared_log_error / n_obs


def mean_squared_log_error(preds: Array, target: Array) -> Array:
    """Mean squared log error."""
    sum_squared_log_error, n_obs = _mean_squared_log_error_update(preds, target)
    return _mean_squared_log_error_compute(sum_squared_log_error, n_obs)
