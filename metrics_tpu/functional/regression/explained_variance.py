"""Explained variance functional (reference: functional/regression/explained_variance.py:20-120)."""
from typing import Tuple, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _check_same_shape

ALLOWED_MULTIOUTPUT = ("raw_values", "uniform_average", "variance_weighted")


def _explained_variance_update(preds: Array, target: Array) -> Tuple[int, Array, Array, Array, Array]:
    _check_same_shape(preds, target)
    preds = jnp.asarray(preds, jnp.float32)
    target = jnp.asarray(target, jnp.float32)
    n_obs = preds.shape[0]
    diff = target - preds
    sum_error = jnp.sum(diff, axis=0)
    sum_squared_error = jnp.sum(diff * diff, axis=0)
    sum_target = jnp.sum(target, axis=0)
    sum_squared_target = jnp.sum(target * target, axis=0)
    return n_obs, sum_error, sum_squared_error, sum_target, sum_squared_target


def _explained_variance_compute(
    n_obs: Union[int, Array],
    sum_error: Array,
    sum_squared_error: Array,
    sum_target: Array,
    sum_squared_target: Array,
    multioutput: str = "uniform_average",
) -> Array:
    diff_avg = sum_error / n_obs
    numerator = sum_squared_error / n_obs - diff_avg**2
    target_avg = sum_target / n_obs
    denominator = sum_squared_target / n_obs - target_avg**2

    nonzero_numerator = numerator != 0
    nonzero_denominator = denominator != 0
    valid_score = nonzero_numerator & nonzero_denominator
    output_scores = jnp.ones_like(diff_avg)
    output_scores = jnp.where(
        valid_score, 1.0 - (numerator / jnp.where(valid_score, denominator, 1.0)), output_scores
    )
    output_scores = jnp.where(nonzero_numerator & ~nonzero_denominator, 0.0, output_scores)

    if multioutput == "raw_values":
        return output_scores
    if multioutput == "uniform_average":
        return jnp.mean(output_scores)
    if multioutput == "variance_weighted":
        denom_sum = jnp.sum(denominator)
        return jnp.sum(denominator / denom_sum * output_scores)
    raise ValueError(f"Argument `multioutput` must be one of {ALLOWED_MULTIOUTPUT}, got {multioutput}")


def explained_variance(preds: Array, target: Array, multioutput: str = "uniform_average") -> Array:
    """Explained variance."""
    if multioutput not in ALLOWED_MULTIOUTPUT:
        raise ValueError(f"Invalid input to argument `multioutput`. Choose one of the following: {ALLOWED_MULTIOUTPUT}")
    n_obs, sum_error, ss_error, sum_target, ss_target = _explained_variance_update(preds, target)
    return _explained_variance_compute(n_obs, sum_error, ss_error, sum_target, ss_target, multioutput)
