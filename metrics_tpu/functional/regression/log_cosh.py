"""LogCosh functional (reference: functional/regression/log_cosh.py:29-93)."""
from typing import Tuple

import jax.numpy as jnp
from jax import Array

from metrics_tpu.utils.checks import _as_float, _check_same_shape


def _unsqueeze_tensors(preds: Array, target: Array) -> Tuple[Array, Array]:
    if preds.ndim == 2:
        return preds, target
    return preds[:, None], target[:, None]


def _log_cosh_error_update(preds: Array, target: Array, num_outputs: int) -> Tuple[Array, Array]:
    _check_same_shape(preds, target)
    preds = _as_float(preds)  # dtype-preserving (tmsan TMS-UPCAST)
    target = _as_float(target)
    preds, target = _unsqueeze_tensors(preds, target)
    diff = preds - target
    # numerically-stable log cosh: |d| + log1p(exp(-2|d|)) - log 2
    sum_log_cosh_error = jnp.squeeze((jnp.abs(diff) + jnp.log1p(jnp.exp(-2 * jnp.abs(diff))) - jnp.log(2.0)).sum(0))
    return sum_log_cosh_error, jnp.asarray(target.shape[0])


def _log_cosh_error_compute(sum_log_cosh_error: Array, n_obs: Array) -> Array:
    return jnp.squeeze(sum_log_cosh_error / n_obs)


def log_cosh_error(preds: Array, target: Array) -> Array:
    """LogCosh error."""
    sum_log_cosh_error, n_obs = _log_cosh_error_update(
        preds, target, num_outputs=1 if preds.ndim == 1 else preds.shape[-1]
    )
    return _log_cosh_error_compute(sum_log_cosh_error, n_obs)
