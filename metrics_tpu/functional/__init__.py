from metrics_tpu.functional.nominal import (
    cramers_v,
    cramers_v_matrix,
    pearsons_contingency_coefficient,
    pearsons_contingency_coefficient_matrix,
    theils_u,
    theils_u_matrix,
    tschuprows_t,
    tschuprows_t_matrix,
)
from metrics_tpu.functional.detection import (
    complete_intersection_over_union,
    distance_intersection_over_union,
    generalized_intersection_over_union,
    intersection_over_union,
)
from metrics_tpu.functional.detection._deprecated import _modified_panoptic_quality as modified_panoptic_quality  # noqa: E402
from metrics_tpu.functional.detection._deprecated import _panoptic_quality as panoptic_quality  # noqa: E402
from metrics_tpu.functional.audio import (
    perceptual_evaluation_speech_quality,
    short_time_objective_intelligibility,
)
from metrics_tpu.functional.audio._deprecated import _permutation_invariant_training as permutation_invariant_training  # noqa: E402
from metrics_tpu.functional.audio._deprecated import _pit_permutate as pit_permutate  # noqa: E402
from metrics_tpu.functional.audio._deprecated import _scale_invariant_signal_distortion_ratio as scale_invariant_signal_distortion_ratio  # noqa: E402
from metrics_tpu.functional.audio._deprecated import _scale_invariant_signal_noise_ratio as scale_invariant_signal_noise_ratio  # noqa: E402
from metrics_tpu.functional.audio._deprecated import _signal_distortion_ratio as signal_distortion_ratio  # noqa: E402
from metrics_tpu.functional.audio._deprecated import _signal_noise_ratio as signal_noise_ratio  # noqa: E402
from metrics_tpu.functional.multimodal import clip_score
from metrics_tpu.functional.text._deprecated import _bert_score as bert_score  # noqa: E402
from metrics_tpu.functional.text._deprecated import _bleu_score as bleu_score  # noqa: E402
from metrics_tpu.functional.text._deprecated import _char_error_rate as char_error_rate  # noqa: E402
from metrics_tpu.functional.text._deprecated import _chrf_score as chrf_score  # noqa: E402
from metrics_tpu.functional.text._deprecated import _extended_edit_distance as extended_edit_distance  # noqa: E402
from metrics_tpu.functional.text._deprecated import _infolm as infolm  # noqa: E402
from metrics_tpu.functional.text._deprecated import _match_error_rate as match_error_rate  # noqa: E402
from metrics_tpu.functional.text._deprecated import _perplexity as perplexity  # noqa: E402
from metrics_tpu.functional.text._deprecated import _rouge_score as rouge_score  # noqa: E402
from metrics_tpu.functional.text._deprecated import _sacre_bleu_score as sacre_bleu_score  # noqa: E402
from metrics_tpu.functional.text._deprecated import _squad as squad  # noqa: E402
from metrics_tpu.functional.text._deprecated import _translation_edit_rate as translation_edit_rate  # noqa: E402
from metrics_tpu.functional.text._deprecated import _word_error_rate as word_error_rate  # noqa: E402
from metrics_tpu.functional.text._deprecated import _word_information_lost as word_information_lost  # noqa: E402
from metrics_tpu.functional.text._deprecated import _word_information_preserved as word_information_preserved  # noqa: E402
from metrics_tpu.functional.pairwise import (
    pairwise_cosine_similarity,
    pairwise_euclidean_distance,
    pairwise_linear_similarity,
    pairwise_manhattan_distance,
    pairwise_minkowski_distance,
)
from metrics_tpu.functional.image import (
    learned_perceptual_image_patch_similarity,
    peak_signal_noise_ratio_with_blocked_effect,
)
from metrics_tpu.functional.image._deprecated import _error_relative_global_dimensionless_synthesis as error_relative_global_dimensionless_synthesis  # noqa: E402
from metrics_tpu.functional.image._deprecated import _image_gradients as image_gradients  # noqa: E402
from metrics_tpu.functional.image._deprecated import _multiscale_structural_similarity_index_measure as multiscale_structural_similarity_index_measure  # noqa: E402
from metrics_tpu.functional.image._deprecated import _peak_signal_noise_ratio as peak_signal_noise_ratio  # noqa: E402
from metrics_tpu.functional.image._deprecated import _relative_average_spectral_error as relative_average_spectral_error  # noqa: E402
from metrics_tpu.functional.image._deprecated import _root_mean_squared_error_using_sliding_window as root_mean_squared_error_using_sliding_window  # noqa: E402
from metrics_tpu.functional.image._deprecated import _spectral_angle_mapper as spectral_angle_mapper  # noqa: E402
from metrics_tpu.functional.image._deprecated import _spectral_distortion_index as spectral_distortion_index  # noqa: E402
from metrics_tpu.functional.image._deprecated import _structural_similarity_index_measure as structural_similarity_index_measure  # noqa: E402
from metrics_tpu.functional.image._deprecated import _total_variation as total_variation  # noqa: E402
from metrics_tpu.functional.image._deprecated import _universal_image_quality_index as universal_image_quality_index  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_average_precision as retrieval_average_precision  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_fall_out as retrieval_fall_out  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_hit_rate as retrieval_hit_rate  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_normalized_dcg as retrieval_normalized_dcg  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_precision as retrieval_precision  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_precision_recall_curve as retrieval_precision_recall_curve  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_r_precision as retrieval_r_precision  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_recall as retrieval_recall  # noqa: E402
from metrics_tpu.functional.retrieval._deprecated import _retrieval_reciprocal_rank as retrieval_reciprocal_rank  # noqa: E402
from metrics_tpu.functional.regression import (
    concordance_corrcoef,
    cosine_similarity,
    explained_variance,
    kendall_rank_corrcoef,
    kl_divergence,
    log_cosh_error,
    mean_absolute_error,
    mean_absolute_percentage_error,
    mean_squared_error,
    mean_squared_log_error,
    minkowski_distance,
    pearson_corrcoef,
    r2_score,
    spearman_corrcoef,
    symmetric_mean_absolute_percentage_error,
    tweedie_deviance_score,
    weighted_mean_absolute_percentage_error,
)
from metrics_tpu.functional.classification import (
    binary_calibration_error,
    binary_hinge_loss,
    binary_precision_at_fixed_recall,
    binary_recall_at_fixed_precision,
    calibration_error,
    dice,
    exact_match,
    hinge_loss,
    multiclass_calibration_error,
    multiclass_exact_match,
    multiclass_hinge_loss,
    multiclass_precision_at_fixed_recall,
    multiclass_recall_at_fixed_precision,
    multilabel_exact_match,
    multilabel_precision_at_fixed_recall,
    multilabel_recall_at_fixed_precision,
    precision_at_fixed_recall,
    recall_at_fixed_precision,

    auroc,
    average_precision,
    binary_auroc,
    binary_average_precision,
    binary_precision_recall_curve,
    binary_roc,
    multiclass_auroc,
    multiclass_average_precision,
    multiclass_precision_recall_curve,
    multiclass_roc,
    multilabel_auroc,
    multilabel_average_precision,
    multilabel_precision_recall_curve,
    multilabel_roc,
    precision_recall_curve,
    roc,

    binary_cohen_kappa,
    binary_confusion_matrix,
    binary_jaccard_index,
    binary_matthews_corrcoef,
    cohen_kappa,
    confusion_matrix,
    jaccard_index,
    matthews_corrcoef,
    multiclass_cohen_kappa,
    multiclass_confusion_matrix,
    multiclass_jaccard_index,
    multiclass_matthews_corrcoef,
    multilabel_confusion_matrix,
    multilabel_jaccard_index,
    multilabel_matthews_corrcoef,

    accuracy,
    binary_accuracy,
    binary_f1_score,
    binary_fbeta_score,
    binary_hamming_distance,
    binary_precision,
    binary_recall,
    binary_specificity,
    binary_stat_scores,
    f1_score,
    fbeta_score,
    hamming_distance,
    multiclass_accuracy,
    multiclass_f1_score,
    multiclass_fbeta_score,
    multiclass_hamming_distance,
    multiclass_precision,
    multiclass_recall,
    multiclass_specificity,
    multiclass_stat_scores,
    multilabel_accuracy,
    multilabel_f1_score,
    multilabel_fbeta_score,
    multilabel_hamming_distance,
    multilabel_precision,
    multilabel_recall,
    multilabel_specificity,
    multilabel_stat_scores,
    precision,
    recall,
    specificity,
    stat_scores,
)

__all__ = [
    "binary_calibration_error",
    "binary_hinge_loss",
    "binary_precision_at_fixed_recall",
    "binary_recall_at_fixed_precision",
    "calibration_error",
    "dice",
    "exact_match",
    "hinge_loss",
    "multiclass_calibration_error",
    "multiclass_exact_match",
    "multiclass_hinge_loss",
    "multiclass_precision_at_fixed_recall",
    "multiclass_recall_at_fixed_precision",
    "multilabel_exact_match",
    "multilabel_precision_at_fixed_recall",
    "multilabel_recall_at_fixed_precision",
    "precision_at_fixed_recall",
    "recall_at_fixed_precision",


    "cramers_v",
    "cramers_v_matrix",
    "pearsons_contingency_coefficient",
    "pearsons_contingency_coefficient_matrix",
    "theils_u",
    "theils_u_matrix",
    "tschuprows_t",
    "tschuprows_t_matrix",
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pairwise_minkowski_distance",

    "error_relative_global_dimensionless_synthesis",
    "image_gradients",
    "multiscale_structural_similarity_index_measure",
    "learned_perceptual_image_patch_similarity",
    "peak_signal_noise_ratio",
    "peak_signal_noise_ratio_with_blocked_effect",
    "relative_average_spectral_error",
    "root_mean_squared_error_using_sliding_window",
    "spectral_angle_mapper",
    "spectral_distortion_index",
    "structural_similarity_index_measure",
    "total_variation",
    "universal_image_quality_index",

    "retrieval_average_precision",
    "retrieval_fall_out",
    "retrieval_hit_rate",
    "retrieval_normalized_dcg",
    "retrieval_precision",
    "retrieval_precision_recall_curve",
    "retrieval_r_precision",
    "retrieval_recall",
    "retrieval_reciprocal_rank",

    "concordance_corrcoef",
    "cosine_similarity",
    "explained_variance",
    "kendall_rank_corrcoef",
    "kl_divergence",
    "log_cosh_error",
    "mean_absolute_error",
    "mean_absolute_percentage_error",
    "mean_squared_error",
    "mean_squared_log_error",
    "minkowski_distance",
    "pearson_corrcoef",
    "r2_score",
    "spearman_corrcoef",
    "symmetric_mean_absolute_percentage_error",
    "tweedie_deviance_score",
    "weighted_mean_absolute_percentage_error",

    "auroc",
    "average_precision",
    "binary_auroc",
    "binary_average_precision",
    "binary_precision_recall_curve",
    "binary_roc",
    "multiclass_auroc",
    "multiclass_average_precision",
    "multiclass_precision_recall_curve",
    "multiclass_roc",
    "multilabel_auroc",
    "multilabel_average_precision",
    "multilabel_precision_recall_curve",
    "multilabel_roc",
    "precision_recall_curve",
    "roc",

    "binary_cohen_kappa",
    "binary_confusion_matrix",
    "binary_jaccard_index",
    "binary_matthews_corrcoef",
    "cohen_kappa",
    "confusion_matrix",
    "jaccard_index",
    "matthews_corrcoef",
    "multiclass_cohen_kappa",
    "multiclass_confusion_matrix",
    "multiclass_jaccard_index",
    "multiclass_matthews_corrcoef",
    "multilabel_confusion_matrix",
    "multilabel_jaccard_index",
    "multilabel_matthews_corrcoef",

    "accuracy",
    "binary_accuracy",
    "binary_f1_score",
    "binary_fbeta_score",
    "binary_hamming_distance",
    "binary_precision",
    "binary_recall",
    "binary_specificity",
    "binary_stat_scores",
    "f1_score",
    "fbeta_score",
    "hamming_distance",
    "multiclass_accuracy",
    "multiclass_f1_score",
    "multiclass_fbeta_score",
    "multiclass_hamming_distance",
    "multiclass_precision",
    "multiclass_recall",
    "multiclass_specificity",
    "multiclass_stat_scores",
    "multilabel_accuracy",
    "multilabel_f1_score",
    "multilabel_fbeta_score",
    "multilabel_hamming_distance",
    "multilabel_precision",
    "multilabel_recall",
    "multilabel_specificity",
    "multilabel_stat_scores",
    "precision",
    "recall",
    "specificity",
    "stat_scores",

    "perceptual_evaluation_speech_quality",
    "permutation_invariant_training",
    "pit_permutate",
    "scale_invariant_signal_distortion_ratio",
    "scale_invariant_signal_noise_ratio",
    "short_time_objective_intelligibility",
    "signal_distortion_ratio",
    "signal_noise_ratio",

    "bert_score",
    "clip_score",
    "bleu_score",
    "char_error_rate",
    "chrf_score",
    "extended_edit_distance",
    "infolm",
    "match_error_rate",
    "perplexity",
    "rouge_score",
    "sacre_bleu_score",
    "squad",
    "translation_edit_rate",
    "word_error_rate",
    "word_information_lost",
    "word_information_preserved",

    "complete_intersection_over_union",
    "distance_intersection_over_union",
    "generalized_intersection_over_union",
    "intersection_over_union",
    "modified_panoptic_quality",
    "panoptic_quality",
]
