"""Pairwise linear similarity (reference: functional/pairwise/linear.py)."""
from typing import Optional

from jax import Array

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal
from metrics_tpu.utils.compute import _safe_matmul


def _pairwise_linear_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise linear similarity matrix (reference: linear.py:23-38)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = _safe_matmul(x, y)
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return distance


def pairwise_linear_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise linear similarity ``<x_i, y_j>`` (reference: linear.py:41-82).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.pairwise import pairwise_linear_similarity
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_linear_similarity(x, y)
        Array([[ 2.,  7.],
               [ 3., 11.],
               [ 5., 18.]], dtype=float32)
    """
    distance = _pairwise_linear_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
