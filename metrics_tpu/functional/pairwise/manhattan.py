"""Pairwise manhattan distance (reference: functional/pairwise/manhattan.py)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal


def _pairwise_manhattan_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise manhattan distance matrix (reference: manhattan.py:22-37)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    distance = jnp.abs(x[:, None, :] - y[None, :, :]).sum(axis=-1)
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return distance


def pairwise_manhattan_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise manhattan distance between rows of ``x`` (and ``y``) (reference: manhattan.py:40-81).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.pairwise import pairwise_manhattan_distance
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_manhattan_distance(x, y)
        Array([[ 4.,  2.],
               [ 7.,  5.],
               [12., 10.]], dtype=float32)
    """
    distance = _pairwise_manhattan_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
