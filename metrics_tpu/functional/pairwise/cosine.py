"""Pairwise cosine similarity (reference: functional/pairwise/cosine.py)."""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal
from metrics_tpu.utils.compute import _safe_matmul


def _pairwise_cosine_similarity_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise cosine similarity matrix (reference: cosine.py:24-45)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    x = x / jnp.linalg.norm(x, ord=2, axis=1, keepdims=True)
    y = y / jnp.linalg.norm(y, ord=2, axis=1, keepdims=True)
    distance = _safe_matmul(x, y)
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return distance


def pairwise_cosine_similarity(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise cosine similarity between rows of ``x`` (and ``y``) (reference: cosine.py:48-95).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.pairwise import pairwise_cosine_similarity
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_cosine_similarity(x, y)
        Array([[0.5547002 , 0.86824316],
               [0.5144958 , 0.84366155],
               [0.52999896, 0.85328186]], dtype=float32)
    """
    distance = _pairwise_cosine_similarity_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
