"""Pairwise minkowski distance (reference: functional/pairwise/minkowski.py)."""
from typing import Optional, Union

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal
from metrics_tpu.utils.exceptions import MetricsUserError


def _pairwise_minkowski_distance_update(
    x: Array, y: Optional[Array] = None, exponent: Union[int, float] = 2, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise minkowski distance matrix (reference: minkowski.py:24-46)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    if not (isinstance(exponent, (float, int)) and exponent >= 1):
        raise MetricsUserError(f"Argument ``p`` must be a float or int greater than 1, but got {exponent}")
    import jax

    _orig_dtype = x.dtype
    acc_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    x = x.astype(acc_dtype)
    y = y.astype(acc_dtype)
    distance = (jnp.abs(x[:, None, :] - y[None, :, :]) ** exponent).sum(axis=-1) ** (1.0 / exponent)
    distance = distance.astype(_orig_dtype)
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return distance


def pairwise_minkowski_distance(
    x: Array,
    y: Optional[Array] = None,
    exponent: Union[int, float] = 2,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise minkowski distance between rows of ``x`` (and ``y``) (reference: minkowski.py:49-94).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.pairwise import pairwise_minkowski_distance
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_minkowski_distance(x, y, exponent=4)
        Array([[3.0092168, 2.       ],
               [5.0316973, 4.0039005],
               [8.122172 , 7.0583053]], dtype=float32)
    """
    distance = _pairwise_minkowski_distance_update(x, y, exponent, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
