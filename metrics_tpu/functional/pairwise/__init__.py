from metrics_tpu.functional.pairwise.cosine import pairwise_cosine_similarity
from metrics_tpu.functional.pairwise.euclidean import pairwise_euclidean_distance
from metrics_tpu.functional.pairwise.linear import pairwise_linear_similarity
from metrics_tpu.functional.pairwise.manhattan import pairwise_manhattan_distance
from metrics_tpu.functional.pairwise.minkowski import pairwise_minkowski_distance

__all__ = [
    "pairwise_cosine_similarity",
    "pairwise_euclidean_distance",
    "pairwise_linear_similarity",
    "pairwise_manhattan_distance",
    "pairwise_minkowski_distance",
]
