"""Pairwise euclidean distance (reference: functional/pairwise/euclidean.py).

TPU note: the ``x_norm + y_norm - 2 x y^T`` decomposition keeps the O(N*M*d) work in
one MXU matmul instead of a broadcasted subtract; the reference's float64 upcast maps
to float64-under-x64 / float32 otherwise (TPU default).
"""
from typing import Optional

import jax.numpy as jnp
from jax import Array

from metrics_tpu.functional.pairwise.helpers import _check_input, _reduce_distance_matrix, _zero_diagonal


def _pairwise_euclidean_distance_update(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Array:
    """Pairwise euclidean distance matrix (reference: euclidean.py:23-43)."""
    x, y, zero_diagonal = _check_input(x, y, zero_diagonal)
    import jax

    _orig_dtype = x.dtype
    acc_dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    x = x.astype(acc_dtype)
    y = y.astype(acc_dtype)
    x_norm = (x * x).sum(axis=1, keepdims=True)
    y_norm = (y * y).sum(axis=1)
    distance = (x_norm + y_norm - 2 * x @ y.T).astype(_orig_dtype)
    if zero_diagonal:
        distance = _zero_diagonal(distance)
    return jnp.sqrt(jnp.maximum(distance, 0.0))


def pairwise_euclidean_distance(
    x: Array,
    y: Optional[Array] = None,
    reduction: Optional[str] = None,
    zero_diagonal: Optional[bool] = None,
) -> Array:
    """Pairwise euclidean distance between rows of ``x`` (and ``y``) (reference: euclidean.py:46-87).

    Example:
        >>> import jax.numpy as jnp
        >>> from metrics_tpu.functional.pairwise import pairwise_euclidean_distance
        >>> x = jnp.array([[2., 3.], [3., 5.], [5., 8.]])
        >>> y = jnp.array([[1., 0.], [2., 1.]])
        >>> pairwise_euclidean_distance(x, y)
        Array([[3.1622777, 2.       ],
               [5.3851647, 4.1231055],
               [8.944272 , 7.615773 ]], dtype=float32)
    """
    distance = _pairwise_euclidean_distance_update(x, y, zero_diagonal)
    return _reduce_distance_matrix(distance, reduction)
