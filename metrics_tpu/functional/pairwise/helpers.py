"""Shared helpers for pairwise metrics (reference: functional/pairwise/helpers.py)."""
from typing import Optional, Tuple

import jax.numpy as jnp
from jax import Array


def _check_input(
    x: Array, y: Optional[Array] = None, zero_diagonal: Optional[bool] = None
) -> Tuple[Array, Array, bool]:
    """Validate shapes and default ``zero_diagonal`` (reference: helpers.py:19-42)."""
    x = jnp.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"Expected argument `x` to be a 2D tensor of shape `[N, d]` but got {x.shape}")

    if y is not None:
        y = jnp.asarray(y)
        if y.ndim != 2 or y.shape[1] != x.shape[1]:
            raise ValueError(
                "Expected argument `y` to be a 2D tensor of shape `[M, d]` where"
                " `d` should be same as the last dimension of `x`"
            )
        zero_diagonal = False if zero_diagonal is None else zero_diagonal
    else:
        y = x
        zero_diagonal = True if zero_diagonal is None else zero_diagonal
    return x, y, zero_diagonal


def _zero_diagonal(distance: Array) -> Array:
    n = min(distance.shape)
    return distance.at[jnp.arange(n), jnp.arange(n)].set(0)


def _reduce_distance_matrix(distmat: Array, reduction: Optional[str] = None) -> Array:
    """Reduce an ``[N, M]`` distance matrix along the last dim (reference: helpers.py:45-58)."""
    if reduction == "mean":
        return distmat.mean(axis=-1)
    if reduction == "sum":
        return distmat.sum(axis=-1)
    if reduction is None or reduction == "none":
        return distmat
    raise ValueError(f"Expected reduction to be one of `['mean', 'sum', None]` but got {reduction}")
