from metrics_tpu.functional.nominal.cramers import cramers_v, cramers_v_matrix
from metrics_tpu.functional.nominal.pearson import (
    pearsons_contingency_coefficient,
    pearsons_contingency_coefficient_matrix,
)
from metrics_tpu.functional.nominal.theils_u import theils_u, theils_u_matrix
from metrics_tpu.functional.nominal.tschuprows import tschuprows_t, tschuprows_t_matrix

__all__ = [
    "cramers_v",
    "cramers_v_matrix",
    "pearsons_contingency_coefficient",
    "pearsons_contingency_coefficient_matrix",
    "theils_u",
    "theils_u_matrix",
    "tschuprows_t",
    "tschuprows_t_matrix",
]
