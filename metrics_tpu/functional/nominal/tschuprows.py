"""Tschuprow's T functionals (reference: functional/nominal/tschuprows.py)."""
import itertools
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from metrics_tpu.functional.nominal.utils import (
    _format_and_densify,
    _validate_dense_labels,
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_input_validation,
    _unable_to_use_bias_correction_warning,
)


def _tschuprows_t_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Confusion-matrix bins (reference: tschuprows.py:32-55)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    _validate_dense_labels(preds, target, num_classes)
    return _multiclass_confusion_matrix_update(
        preds.astype(jnp.int32).ravel(), target.astype(jnp.int32).ravel(), num_classes
    )


def _tschuprows_t_compute(confmat: Array, bias_correction: bool) -> Array:
    """Tschuprow's T from a confusion matrix (reference: tschuprows.py:58-87)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    n_rows, n_cols = confmat.shape

    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, n_rows, n_cols, cm_sum
        )
        if float(jnp.minimum(rows_corrected, cols_corrected)) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Tschuprow's T")
            return jnp.asarray(jnp.nan)
        value = jnp.sqrt(phi_squared_corrected / jnp.sqrt((rows_corrected - 1) * (cols_corrected - 1)))
    else:
        value = jnp.sqrt(phi_squared / jnp.sqrt(jnp.asarray((n_rows - 1) * (n_cols - 1), jnp.float32)))
    return jnp.clip(value, 0.0, 1.0)


def tschuprows_t(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Tschuprow's T between two categorical series (reference: tschuprows.py:90-141).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.nominal import tschuprows_t
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> 0 <= float(tschuprows_t(preds, target)) <= 1
        True
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _format_and_densify(preds, target, nan_strategy, nan_replace_value)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _tschuprows_t_compute(confmat, bias_correction)


def tschuprows_t_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Tschuprow's T between all pairs of columns (reference: tschuprows.py:144-186)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        x, y, num_classes = _format_and_densify(x, y, nan_strategy, nan_replace_value)
        confmat = _multiclass_confusion_matrix_update(x, y, num_classes)
        out[i, j] = out[j, i] = float(_tschuprows_t_compute(confmat, bias_correction))
    return jnp.asarray(out)
