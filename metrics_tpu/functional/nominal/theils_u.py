"""Theil's U functionals (reference: functional/nominal/theils_u.py)."""
import itertools
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from metrics_tpu.functional.nominal.utils import (
    _format_and_densify,
    _validate_dense_labels,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_input_validation,
)


def _conditional_entropy_compute(confmat: Array) -> Array:
    """Conditional entropy H(X|Y) from a confusion matrix (reference: theils_u.py:30-51)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    total_occurrences = confmat.sum()
    p_xy_m = confmat / total_occurrences
    p_y = confmat.sum(1) / total_occurrences
    p_y_m = jnp.repeat(p_y[:, None], p_xy_m.shape[1], axis=1)
    return jnp.nansum(p_xy_m * jnp.log(p_y_m / p_xy_m))


def _theils_u_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Confusion-matrix bins (reference: theils_u.py:54-76)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    _validate_dense_labels(preds, target, num_classes)
    return _multiclass_confusion_matrix_update(
        preds.astype(jnp.int32).ravel(), target.astype(jnp.int32).ravel(), num_classes
    )


def _theils_u_compute(confmat: Array) -> Array:
    """Theil's U from a confusion matrix (reference: theils_u.py:79-101)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    s_xy = _conditional_entropy_compute(confmat)
    total_occurrences = confmat.sum()
    p_x = confmat.sum(0) / total_occurrences
    s_x = -jnp.sum(p_x * jnp.log(p_x))
    if float(s_x) == 0:
        return jnp.asarray(0.0)
    return (s_x - s_xy) / s_x


def theils_u(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Theil's U (uncertainty coefficient) between two categorical series (reference: theils_u.py:104-147).

    Asymmetric: ``theils_u(preds, target) != theils_u(target, preds)`` in general.

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.nominal import theils_u
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> 0 <= float(theils_u(preds, target)) <= 1
        True
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _format_and_densify(preds, target, nan_strategy, nan_replace_value)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _theils_u_compute(confmat)


def theils_u_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Theil's U between all pairs of columns, asymmetric (reference: theils_u.py:150-190)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        x, y, num_classes = _format_and_densify(x, y, nan_strategy, nan_replace_value)
        confmat = _multiclass_confusion_matrix_update(x, y, num_classes)
        out[i, j] = float(_theils_u_compute(confmat))
        out[j, i] = float(_theils_u_compute(confmat.T))
    return jnp.asarray(out)
