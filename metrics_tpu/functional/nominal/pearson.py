"""Pearson's Contingency Coefficient functionals (reference: functional/nominal/pearson.py)."""
import itertools
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from metrics_tpu.functional.nominal.utils import (
    _format_and_densify,
    _validate_dense_labels,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_input_validation,
)


def _pearsons_contingency_coefficient_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Confusion-matrix bins (reference: pearson.py:30-53)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    _validate_dense_labels(preds, target, num_classes)
    return _multiclass_confusion_matrix_update(
        preds.astype(jnp.int32).ravel(), target.astype(jnp.int32).ravel(), num_classes
    )


def _pearsons_contingency_coefficient_compute(confmat: Array) -> Array:
    """Pearson's contingency coefficient from a confusion matrix (reference: pearson.py:56-71)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction=False)
    phi_squared = chi_squared / cm_sum
    value = jnp.sqrt(phi_squared / (1 + phi_squared))
    return jnp.clip(value, 0.0, 1.0)


def pearsons_contingency_coefficient(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Pearson's Contingency Coefficient between two categorical series (reference: pearson.py:74-125).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.nominal import pearsons_contingency_coefficient
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> 0 <= float(pearsons_contingency_coefficient(preds, target)) <= 1
        True
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _format_and_densify(preds, target, nan_strategy, nan_replace_value)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _pearsons_contingency_coefficient_compute(confmat)


def pearsons_contingency_coefficient_matrix(
    matrix: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Pearson's contingency coefficient between all pairs of columns (reference: pearson.py:128-170)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        x, y, num_classes = _format_and_densify(x, y, nan_strategy, nan_replace_value)
        confmat = _multiclass_confusion_matrix_update(x, y, num_classes)
        out[i, j] = out[j, i] = float(_pearsons_contingency_coefficient_compute(confmat))
    return jnp.asarray(out)
