"""Shared helpers for nominal-association metrics (reference: functional/nominal/utils.py)."""
from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.utils.checks import _is_concrete
from metrics_tpu.utils.prints import rank_zero_warn


def _nominal_input_validation(nan_strategy: str, nan_replace_value: Optional[Union[int, float]]) -> None:
    """Reference: utils.py:23-32."""
    if nan_strategy not in ["replace", "drop"]:
        raise ValueError(
            f"Argument `nan_strategy` is expected to be one of `['replace', 'drop']`, but got {nan_strategy}"
        )
    if nan_strategy == "replace" and not isinstance(nan_replace_value, (int, float)):
        raise ValueError(
            "Argument `nan_replace` is expected to be of a type `int` or `float` when `nan_strategy = 'replace`, "
            f"but got {nan_replace_value}"
        )


def _compute_expected_freqs(confmat: Array) -> Array:
    """Expected frequencies under independence (reference: utils.py:35-38)."""
    margin_sum_rows, margin_sum_cols = confmat.sum(1), confmat.sum(0)
    return jnp.outer(margin_sum_rows, margin_sum_cols) / confmat.sum()


def _compute_chi_squared(confmat: Array, bias_correction: bool) -> Array:
    """Chi-square independence statistic (reference: utils.py:41-58)."""
    expected_freqs = _compute_expected_freqs(confmat)
    df = expected_freqs.size - sum(expected_freqs.shape) + expected_freqs.ndim - 1
    if df == 0:
        return jnp.asarray(0.0)
    if df == 1 and bias_correction:
        diff = expected_freqs - confmat
        direction = jnp.sign(diff)
        confmat = confmat + direction * jnp.minimum(0.5 * jnp.ones_like(direction), jnp.abs(diff))
    return jnp.sum((confmat - expected_freqs) ** 2 / expected_freqs)


def _drop_empty_rows_and_cols(confmat: Array) -> Array:
    """Drop all-zero rows/columns; compute-time host op (reference: utils.py:61-79)."""
    cm = np.asarray(confmat)
    cm = cm[cm.sum(1) != 0]
    cm = cm[:, cm.sum(0) != 0]
    return jnp.asarray(cm)


def _compute_phi_squared_corrected(
    phi_squared: Array, n_rows: int, n_cols: int, confmat_sum: Array
) -> Array:
    """Reference: utils.py:82-91."""
    return jnp.maximum(jnp.asarray(0.0), phi_squared - ((n_rows - 1) * (n_cols - 1)) / (confmat_sum - 1))


def _compute_rows_and_cols_corrected(n_rows: int, n_cols: int, confmat_sum: Array) -> Tuple[Array, Array]:
    """Reference: utils.py:94-98."""
    rows_corrected = n_rows - (n_rows - 1) ** 2 / (confmat_sum - 1)
    cols_corrected = n_cols - (n_cols - 1) ** 2 / (confmat_sum - 1)
    return rows_corrected, cols_corrected


def _compute_bias_corrected_values(
    phi_squared: Array, n_rows: int, n_cols: int, confmat_sum: Array
) -> Tuple[Array, Array, Array]:
    """Reference: utils.py:101-107."""
    phi_squared_corrected = _compute_phi_squared_corrected(phi_squared, n_rows, n_cols, confmat_sum)
    rows_corrected, cols_corrected = _compute_rows_and_cols_corrected(n_rows, n_cols, confmat_sum)
    return phi_squared_corrected, rows_corrected, cols_corrected


def _handle_nan_in_data(
    preds: Array,
    target: Array,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[float] = 0.0,
) -> Tuple[Array, Array]:
    """Replace or drop NaN rows (reference: utils.py:110-137)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    if nan_strategy == "replace":
        return jnp.nan_to_num(preds, nan=nan_replace_value), jnp.nan_to_num(target, nan=nan_replace_value)
    if not _is_concrete(preds, target):
        # data-dependent row count: fail at trace time with a usable message
        # instead of a TracerArrayConversionError from np.isnan (tmlint
        # TM-HOSTSYNC finding, round 7)
        raise ValueError(
            "`nan_strategy='drop'` removes rows by data content and cannot run under"
            " jit/shard_map; use nan_strategy='replace' or drop NaN rows on host"
            " before updating."
        )
    rows_contain_nan = np.logical_or(np.isnan(np.asarray(preds)), np.isnan(np.asarray(target)))
    return preds[~rows_contain_nan], target[~rows_contain_nan]


def _format_and_densify(
    preds: Array,
    target: Array,
    nan_strategy: str,
    nan_replace_value: Optional[Union[int, float]],
) -> Tuple[Array, Array, int]:
    """Format inputs and remap labels to a dense 0-based range.

    The public nominal functionals infer ``num_classes`` from the data; scattering
    with *raw* label values would silently drop non-contiguous or 1-based categories
    (JAX drops out-of-bounds scatter indices — ADVICE r1). Remapping via
    ``np.unique(return_inverse=True)`` makes any hashable label set correct.
    Host-side by design: these one-shot functionals are not jit paths.
    """
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    p = np.asarray(preds).ravel()
    t = np.asarray(target).ravel()
    joint = np.concatenate([p, t])
    uniq, inv = np.unique(joint, return_inverse=True)
    inv = inv.astype(np.int32)
    return jnp.asarray(inv[: p.size]), jnp.asarray(inv[p.size :]), max(len(uniq), 1)


def _validate_dense_labels(preds: Array, target: Array, num_classes: int) -> None:
    """Raise on labels outside ``[0, num_classes)``; skipped under jit tracing.

    The class-based nominal metrics take ``num_classes`` up front; out-of-range
    labels would be silently dropped by the scatter (the torch reference fails
    loudly on the same input — ADVICE r1), so fail loudly here too when concrete.
    """
    if isinstance(preds, jax.core.Tracer) or isinstance(target, jax.core.Tracer):
        return
    p = np.asarray(preds)
    t = np.asarray(target)
    if p.size == 0 or t.size == 0:
        return
    lo = min(p.min(), t.min())
    hi = max(p.max(), t.max())
    if lo < 0 or hi >= num_classes:
        raise ValueError(
            f"Nominal metrics expect dense 0-based labels in [0, {num_classes}), but got values "
            f"in [{lo}, {hi}]. Remap labels first (e.g. np.unique(..., return_inverse=True)) "
            "or construct the metric with a larger `num_classes`."
        )


def _unable_to_use_bias_correction_warning(metric_name: str) -> None:
    rank_zero_warn(
        f"Unable to compute {metric_name} using bias correction. Please consider to set `bias_correction=False`."
    )
