"""Cramer's V functionals (reference: functional/nominal/cramers.py)."""
import itertools
from typing import Optional, Union

import jax.numpy as jnp
import numpy as np
from jax import Array

from metrics_tpu.functional.classification.confusion_matrix import _multiclass_confusion_matrix_update
from metrics_tpu.functional.nominal.utils import (
    _format_and_densify,
    _validate_dense_labels,
    _compute_bias_corrected_values,
    _compute_chi_squared,
    _drop_empty_rows_and_cols,
    _handle_nan_in_data,
    _nominal_input_validation,
    _unable_to_use_bias_correction_warning,
)


def _cramers_v_update(
    preds: Array,
    target: Array,
    num_classes: int,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Confusion-matrix bins for Cramer's V (reference: cramers.py:32-55)."""
    preds = jnp.asarray(preds)
    target = jnp.asarray(target)
    preds = preds.argmax(1) if preds.ndim == 2 else preds
    target = target.argmax(1) if target.ndim == 2 else target
    preds, target = _handle_nan_in_data(preds, target, nan_strategy, nan_replace_value)
    _validate_dense_labels(preds, target, num_classes)
    return _multiclass_confusion_matrix_update(
        preds.astype(jnp.int32).ravel(), target.astype(jnp.int32).ravel(), num_classes
    )


def _cramers_v_compute(confmat: Array, bias_correction: bool) -> Array:
    """Cramer's V from a confusion matrix (reference: cramers.py:58-85)."""
    confmat = _drop_empty_rows_and_cols(confmat)
    cm_sum = confmat.sum()
    chi_squared = _compute_chi_squared(confmat, bias_correction)
    phi_squared = chi_squared / cm_sum
    n_rows, n_cols = confmat.shape

    if bias_correction:
        phi_squared_corrected, rows_corrected, cols_corrected = _compute_bias_corrected_values(
            phi_squared, n_rows, n_cols, cm_sum
        )
        if float(jnp.minimum(rows_corrected, cols_corrected)) == 1:
            _unable_to_use_bias_correction_warning(metric_name="Cramer's V")
            return jnp.asarray(jnp.nan)
        cramers_v_value = jnp.sqrt(phi_squared_corrected / jnp.minimum(rows_corrected - 1, cols_corrected - 1))
    else:
        cramers_v_value = jnp.sqrt(phi_squared / min(n_rows - 1, n_cols - 1))
    return jnp.clip(cramers_v_value, 0.0, 1.0)


def cramers_v(
    preds: Array,
    target: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Cramer's V statistic of association between two categorical series (reference: cramers.py:88-135).

    Example:
        >>> import jax, jax.numpy as jnp
        >>> from metrics_tpu.functional.nominal import cramers_v
        >>> preds = jax.random.randint(jax.random.PRNGKey(42), (100,), 0, 4)
        >>> target = (preds + jax.random.randint(jax.random.PRNGKey(43), (100,), 0, 2)) % 4
        >>> 0 <= float(cramers_v(preds, target)) <= 1
        True
    """
    _nominal_input_validation(nan_strategy, nan_replace_value)
    preds, target, num_classes = _format_and_densify(preds, target, nan_strategy, nan_replace_value)
    confmat = _multiclass_confusion_matrix_update(preds, target, num_classes)
    return _cramers_v_compute(confmat, bias_correction)


def cramers_v_matrix(
    matrix: Array,
    bias_correction: bool = True,
    nan_strategy: str = "replace",
    nan_replace_value: Optional[Union[int, float]] = 0.0,
) -> Array:
    """Cramer's V between all pairs of columns (reference: cramers.py:138-180)."""
    _nominal_input_validation(nan_strategy, nan_replace_value)
    matrix = jnp.asarray(matrix)
    num_variables = matrix.shape[1]
    out = np.ones((num_variables, num_variables), dtype=np.float32)
    for i, j in itertools.combinations(range(num_variables), 2):
        x, y = matrix[:, i], matrix[:, j]
        x, y, num_classes = _format_and_densify(x, y, nan_strategy, nan_replace_value)
        confmat = _multiclass_confusion_matrix_update(x, y, num_classes)
        out[i, j] = out[j, i] = float(_cramers_v_compute(confmat, bias_correction))
    return jnp.asarray(out)
