"""Segment-grouped retrieval kernels.

The TPU-native replacement for the reference's host-side group-by loop
(``retrieval/base.py:113-145``: ``_flexible_bincount(...).cpu().tolist()`` +
``torch.split`` + python loop over queries). Here the whole evaluation is one
device program:

1. one payload-carrying variadic sort groups rows by query and ranks docs by
   score inside each query,
2. segment ids come from boundary detection + cumsum,
3. every per-query retrieval metric becomes a segment reduction (segment_sum /
   segment_min) over rank-indexed terms — no host round-trips, no ragged splits,
   O(N log N) total and fully jit-compatible with a static row count.

Measured design notes (4.2M docs / 65k queries, v5e, device_get-synced p50 —
``block_until_ready`` does not round-trip on the tunneled backend):

- **Gathers are the enemy, not the sort.** The original
  ``order = lexsort(...); x[order]`` layout cost 305 ms, of which the 2-key sort
  itself was only 38 ms — each 4M-row gather costs ~90 ms on TPU. One
  ``lax.sort`` carrying all three columns as payloads does the same layout in
  45 ms (6.8x).
- **Within-segment positions come from scans, not segment_min+gather**:
  ``cummax(where(new_seg, pos, 0))`` broadcasts each segment's start row to its
  members in one associative scan.
- ``indices_are_sorted=True`` on every segment reduction (segment ids are
  sorted by construction) lets XLA skip the scatter's sorting pass.
- Net: RetrievalMAP end-to-end went 8.4 -> 22.0 Mdocs/s (the remaining time is
  the sort at ~45 ms + ~4 linear scans/scatters at ~15-25 ms each; a fused
  one-pass segmented scan would need a hand-written kernel for <2x more).
  Experiment grid: experiments/retrieval_exp.py.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _segment_layout(indexes: Array, preds: Array, target: Array):
    """Sort rows by (query, -score); return per-row segment ids and rank info.

    Returns: (seg_id, rank, sorted_preds, sorted_target, n_seg_upper, seg_count,
    seg_index) where rank is the 1-based position of the row inside its query's
    score-ordered list, seg_count[s] is the number of docs of segment s (0 for unused
    slots), and seg_index[s] is the original query id of segment s (negative values
    mark padding rows whose segment must not count as a real query).
    """
    n = indexes.shape[0]
    # one variadic sort carrying the columns as payloads: measured 6.8x faster
    # than argsort + three 4M-row gathers on TPU (see module docstring)
    _, _, s_idx, s_preds, s_target = jax.lax.sort(
        (indexes, -preds, indexes, preds, target), num_keys=2, is_stable=True
    )

    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    seg_id = jnp.cumsum(new_seg) - 1  # dense 0..n_q-1

    pos = jnp.arange(n)
    # broadcast each segment's start row to its members via one scan (no gather)
    seg_start_row = jax.lax.cummax(jnp.where(new_seg, pos, 0))
    rank = pos - seg_start_row + 1  # 1-based within query

    seg_count = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg_id, num_segments=n, indices_are_sorted=True)
    # first (== any) original index of each segment: negative marks padding rows
    # (cat-buffer fill / pow2 pad), whose segment must not count as a real query
    seg_index = jax.ops.segment_min(s_idx, seg_id, num_segments=n, indices_are_sorted=True)
    return seg_id, rank, s_preds, s_target, n, seg_count, seg_index


def _segment_cumsum_nonneg(values: Array, new_seg: Array) -> Array:
    """Within-segment inclusive cumsum for NON-NEGATIVE values.

    The global cumsum is non-decreasing, so each segment's base (global cumsum
    just before the segment) can be broadcast to its rows with one ``cummax``
    instead of a per-row gather. Callers must guarantee ``values >= 0``.

    Dtype-preserving: count-like streams are passed as int32 so the GLOBAL
    running sum stays exact to 2^31 rows (an f32 global sum would lose integer
    exactness past 2^24 positive rows — the scale this module's own 2^24-row
    benchmarks run at); fractional streams (the AP contribution sum) stay f32,
    where the base-difference is subject to ordinary float rounding only.
    """
    g = jnp.cumsum(values)
    base = jax.lax.cummax(jnp.where(new_seg, g - values, jnp.zeros_like(g)))
    return g - base


# metrics whose per-query value is a segmented-cumsum read at the segment's
# last row: they run with ZERO segment scatters (sort + ~5 scans + plain sums)
_SCAN_METRICS = frozenset(
    {"average_precision", "reciprocal_rank", "precision", "recall", "hit_rate", "fall_out"}
)


def _scan_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int],
    adaptive_k: bool,
) -> Tuple[Array, Array, Array]:
    """Scan-only fast path: per-query score materialized at each segment's LAST
    row; other rows carry 0 / valid=False. The caller's reduction is elementwise
    so row-aligned results are interchangeable with segment-aligned ones.

    Why: ``segment_sum`` (a scatter) costs ~174 ms per call at 2^24 rows on v5e
    even with sorted indices, while ``cumsum``/``cummax`` scans cost ~30 ms; AP
    needs 4+ per-segment reductions. Expressing each as "segmented cumsum value
    at the last row" (base broadcast by ``cummax`` — exact for the non-negative
    summands used here) removes every scatter: 715 -> ~300 ms for the full AP
    kernel at 2^24. (``lax.associative_scan`` segmented scans were rejected:
    the recursive decomposition takes minutes to compile at this size.)
    """
    n = indexes.shape[0]
    _, _, s_idx, s_preds, s_target = jax.lax.sort(
        (indexes, -preds, indexes, preds, target), num_keys=2, is_stable=True
    )
    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    is_last = jnp.concatenate([new_seg[1:], jnp.ones(1, dtype=bool)])
    pos = jnp.arange(n)
    seg_start_row = jax.lax.cummax(jnp.where(new_seg, pos, 0))
    rank = pos - seg_start_row + 1

    # counts run in int32 through the cumsum-base trick: exact to 2^31 rows
    # (f32 would drift past 2^24 positive rows); cast at the read points
    binary_i = (s_target > 0).astype(jnp.int32)
    binary_t = binary_i.astype(jnp.float32)
    in_k = jnp.ones(n, dtype=bool) if top_k is None else rank <= top_k
    in_k_i = in_k.astype(jnp.int32)

    def segcumsum(v):  # within-segment cumsum, v >= 0 (see _segment_cumsum_nonneg)
        return _segment_cumsum_nonneg(v, new_seg)

    cum_rel_k = segcumsum(binary_i * in_k_i).astype(jnp.float32)
    cum_rel = cum_rel_k if top_k is None else segcumsum(binary_i).astype(jnp.float32)
    n_pos = jnp.where(is_last, cum_rel, 0.0)
    valid = is_last & (s_idx >= 0)

    if metric == "fall_out":
        nonrel = 1 - binary_i
        cum_nonrel_k = segcumsum(nonrel * in_k_i).astype(jnp.float32)
        cum_nonrel = cum_nonrel_k if top_k is None else segcumsum(nonrel).astype(jnp.float32)
        n_neg = jnp.where(is_last, cum_nonrel, 0.0)
        scores = jnp.where(is_last & (n_neg > 0), cum_nonrel_k / jnp.maximum(n_neg, 1.0), 0.0)
        return scores, n_neg, valid  # n_positive slot carries negatives for empty handling

    if metric == "average_precision":
        contrib = jnp.where(in_k, binary_t * cum_rel_k / rank, 0.0)
        cum_contrib = segcumsum(contrib)
        scores = jnp.where(is_last & (cum_rel_k > 0), cum_contrib / jnp.maximum(cum_rel_k, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "reciprocal_rank":
        # global cummax of "position+1 of each segment's first relevant row":
        # later segments' markers dominate earlier ones, and the value is only
        # read at last rows of segments that HAVE a relevant row (n_pos > 0)
        marker = jnp.where((binary_t > 0) & (cum_rel == 1), pos + 1, 0)
        first_rel_pos = jax.lax.cummax(marker)
        first_rel_rank = (first_rel_pos - 1 - seg_start_row + 1).astype(jnp.float32)
        scores = jnp.where(is_last & (n_pos > 0), 1.0 / jnp.maximum(first_rel_rank, 1.0), 0.0)
        return scores, n_pos, valid

    count_f = rank.astype(jnp.float32)  # at last row == segment size
    if top_k is None:
        k_per_seg = count_f
    elif adaptive_k:
        k_per_seg = jnp.minimum(float(top_k), count_f)
    else:
        k_per_seg = jnp.full_like(count_f, float(top_k))

    if metric == "precision":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(k_per_seg, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "recall":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "hit_rate":
        scores = jnp.where(is_last & (cum_rel_k > 0), 1.0, 0.0)
        return scores, n_pos, valid

    raise ValueError(f"Metric {metric} is not scan-friendly")


def grouped_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int] = None,
    adaptive_k: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-query scores for every query in one fused device pass.

    Returns ``(scores, n_positive, valid)`` each of length N (the padded row
    count, an upper bound on the number of queries); only entries where
    ``valid`` is True are real queries. ``n_positive`` is the per-query count of
    positive targets (used by the caller for ``empty_target_action`` handling;
    for ``fall_out`` it counts negatives).

    ALIGNMENT CONTRACT — the three arrays are mutually aligned, but WHERE a
    query's entry sits depends on the metric's path:

    - scan metrics (``_SCAN_METRICS``) return ROW-aligned results: a query's
      score/n_positive/valid live at its LAST row in (query, -score) sort order,
      every other row holds 0 / False;
    - ``ndcg`` and ``r_precision`` return SEGMENT-aligned results: entry ``s``
      is the ``s``-th distinct query in sorted order, trailing slots are 0/False.

    Both shapes are length N and support only position-agnostic consumption
    (masked reductions over ``valid``, e.g. ``scores.sum() / valid.sum()``).
    Do NOT slice a prefix (``scores[:n_queries]``) or otherwise assume one of
    the two layouts. Scan metrics avoid every scatter this way; ndcg (summands
    may be negative for float targets, breaking the cummax base trick) and
    r_precision (needs a per-row broadcast of the segment total, i.e. future
    information) keep the segment-reduction layout below.
    """
    if metric in _SCAN_METRICS:
        return _scan_retrieval_scores(indexes, preds, target, metric, top_k, adaptive_k)
    n = indexes.shape[0]
    seg_id, rank, s_preds, s_target, n_seg, seg_count, seg_index = _segment_layout(indexes, preds, target)
    valid = (seg_count > 0) & (seg_index >= 0)
    new_seg = rank == 1
    t = s_target.astype(jnp.float32)
    binary_t = (s_target > 0).astype(jnp.float32)

    count_f = seg_count.astype(jnp.float32)
    if top_k is None:
        k_per_seg = count_f
        in_k = jnp.ones(n, dtype=bool)
    else:
        if adaptive_k:
            k_per_seg = jnp.minimum(float(top_k), count_f)
        else:
            k_per_seg = jnp.full_like(count_f, float(top_k))
        in_k = rank <= top_k

    seg_sum = partial(jax.ops.segment_sum, segment_ids=seg_id, num_segments=n_seg, indices_are_sorted=True)
    n_pos = seg_sum(binary_t)
    n_neg = seg_sum(1.0 - binary_t)

    if metric == "average_precision":
        # AP = mean over relevant-in-topk of (j / rank_j), j = within-query relevant index
        cumrel = _segment_cumsum_nonneg(binary_t * in_k, new_seg)
        contrib = jnp.where(in_k, binary_t * cumrel / rank, 0.0)
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(rel_in_k > 0, seg_sum(contrib) / jnp.maximum(rel_in_k, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "reciprocal_rank":
        first_rel = jax.ops.segment_min(
            jnp.where(binary_t > 0, rank, jnp.iinfo(jnp.int32).max),
            seg_id,
            num_segments=n_seg,
            indices_are_sorted=True,
        )
        scores = jnp.where(n_pos > 0, 1.0 / jnp.maximum(first_rel, 1).astype(jnp.float32), 0.0)
        return scores, n_pos, valid

    if metric == "precision":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(n_pos > 0, rel_in_k / jnp.maximum(k_per_seg, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "recall":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(n_pos > 0, rel_in_k / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "hit_rate":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = (rel_in_k > 0).astype(jnp.float32)
        return scores, n_pos, valid

    if metric == "fall_out":
        # fraction of non-relevant docs retrieved in top-k among all non-relevant
        nonrel_in_k = seg_sum((1.0 - binary_t) * in_k)
        scores = jnp.where(n_neg > 0, nonrel_in_k / jnp.maximum(n_neg, 1.0), 0.0)
        return scores, n_neg, valid  # n_positive slot carries negatives for empty handling

    if metric == "r_precision":
        # relevant among top-(n_pos) ranked docs; the per-row broadcast of the
        # segment's positive count is the one gather this path keeps
        in_r = rank.astype(jnp.float32) <= n_pos[seg_id]
        rel_in_r = seg_sum(binary_t * in_r)
        scores = jnp.where(n_pos > 0, rel_in_r / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "ndcg":
        # DCG over score-ranked targets; IDCG over value-sorted targets
        disc = 1.0 / jnp.log2(rank.astype(jnp.float32) + 1.0)
        dcg = seg_sum(jnp.where(in_k, t * disc, 0.0))
        # ideal ordering: payload sort by (query, -target), same no-gather shape
        _, _, s_t2 = jax.lax.sort((indexes, -target, target), num_keys=2, is_stable=True)
        idcg = seg_sum(jnp.where(in_k, s_t2.astype(jnp.float32) * disc, 0.0))
        scores = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 0.0)
        scores = jnp.clip(scores, 0.0, 1.0)
        return scores, n_pos, valid

    raise ValueError(f"Unknown grouped retrieval metric: {metric}")
