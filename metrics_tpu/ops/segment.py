"""Segment-grouped retrieval kernels.

The TPU-native replacement for the reference's host-side group-by loop
(``retrieval/base.py:113-145``: ``_flexible_bincount(...).cpu().tolist()`` +
``torch.split`` + python loop over queries). Here the whole evaluation is one
device program:

1. one lexsort groups rows by query and ranks docs by score inside each query,
2. segment ids come from boundary detection + cumsum,
3. every per-query retrieval metric becomes a segment reduction (segment_sum /
   segment_min) over rank-indexed terms — no host round-trips, no ragged splits,
   O(N log N) total and fully jit-compatible with a static row count.
"""
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _segment_layout(indexes: Array, preds: Array, target: Array):
    """Sort rows by (query, -score); return per-row segment ids and rank info.

    Returns: (seg_id, rank, sorted_preds, sorted_target, n_seg_upper, seg_count,
    seg_index) where rank is the 1-based position of the row inside its query's
    score-ordered list, seg_count[s] is the number of docs of segment s (0 for unused
    slots), and seg_index[s] is the original query id of segment s (negative values
    mark padding rows whose segment must not count as a real query).
    """
    n = indexes.shape[0]
    order = jnp.lexsort((-preds, indexes))
    s_idx = indexes[order]
    s_preds = preds[order]
    s_target = target[order]

    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    seg_id = jnp.cumsum(new_seg) - 1  # dense 0..n_q-1

    pos = jnp.arange(n)
    seg_start = jax.ops.segment_min(pos, seg_id, num_segments=n)
    rank = pos - seg_start[seg_id] + 1  # 1-based within query

    seg_count = jax.ops.segment_sum(jnp.ones(n, jnp.int32), seg_id, num_segments=n)
    # first (== any) original index of each segment: negative marks padding rows
    # (cat-buffer fill / pow2 pad), whose segment must not count as a real query
    seg_index = jax.ops.segment_min(s_idx, seg_id, num_segments=n)
    return seg_id, rank, s_preds, s_target, n, seg_count, seg_index


def _segment_cumsum(values: Array, seg_id: Array, num_segments: int) -> Array:
    """Within-segment inclusive cumsum via global cumsum minus per-segment base."""
    g = jnp.cumsum(values)
    pos = jnp.arange(values.shape[0])
    start = jax.ops.segment_min(pos, seg_id, num_segments=num_segments)
    base = g[start[seg_id]] - values[start[seg_id]]
    return g - base


def grouped_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int] = None,
    adaptive_k: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-query scores for every query in one fused device pass.

    Returns ``(scores, n_positive, valid)`` each of length N (upper bound on number
    of queries); only entries where ``valid`` is True correspond to real queries.
    ``n_positive`` is the per-query count of positive targets (used by the caller
    for ``empty_target_action`` handling; for ``fall_out`` it counts negatives).
    """
    n = indexes.shape[0]
    seg_id, rank, s_preds, s_target, n_seg, seg_count, seg_index = _segment_layout(indexes, preds, target)
    valid = (seg_count > 0) & (seg_index >= 0)
    t = s_target.astype(jnp.float32)
    binary_t = (s_target > 0).astype(jnp.float32)

    count_f = seg_count.astype(jnp.float32)
    if top_k is None:
        k_per_seg = count_f
        in_k = jnp.ones(n, dtype=bool)
    else:
        if adaptive_k:
            k_per_seg = jnp.minimum(float(top_k), count_f)
        else:
            k_per_seg = jnp.full_like(count_f, float(top_k))
        in_k = rank <= top_k

    seg_sum = partial(jax.ops.segment_sum, segment_ids=seg_id, num_segments=n_seg)
    n_pos = seg_sum(binary_t)
    n_neg = seg_sum(1.0 - binary_t)

    if metric == "average_precision":
        # AP = mean over relevant-in-topk of (j / rank_j), j = within-query relevant index
        cumrel = _segment_cumsum(binary_t * in_k, seg_id, n_seg)
        contrib = jnp.where(in_k, binary_t * cumrel / rank, 0.0)
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(rel_in_k > 0, seg_sum(contrib) / jnp.maximum(rel_in_k, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "reciprocal_rank":
        first_rel = jax.ops.segment_min(
            jnp.where(binary_t > 0, rank, jnp.iinfo(jnp.int32).max), seg_id, num_segments=n_seg
        )
        scores = jnp.where(n_pos > 0, 1.0 / jnp.maximum(first_rel, 1).astype(jnp.float32), 0.0)
        return scores, n_pos, valid

    if metric == "precision":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(n_pos > 0, rel_in_k / jnp.maximum(k_per_seg, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "recall":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = jnp.where(n_pos > 0, rel_in_k / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "hit_rate":
        rel_in_k = seg_sum(binary_t * in_k)
        scores = (rel_in_k > 0).astype(jnp.float32)
        return scores, n_pos, valid

    if metric == "fall_out":
        # fraction of non-relevant docs retrieved in top-k among all non-relevant
        nonrel_in_k = seg_sum((1.0 - binary_t) * in_k)
        scores = jnp.where(n_neg > 0, nonrel_in_k / jnp.maximum(n_neg, 1.0), 0.0)
        return scores, n_neg, valid  # n_positive slot carries negatives for empty handling

    if metric == "r_precision":
        # relevant among top-(n_pos) ranked docs
        in_r = rank.astype(jnp.float32) <= n_pos[seg_id]
        rel_in_r = seg_sum(binary_t * in_r)
        scores = jnp.where(n_pos > 0, rel_in_r / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "ndcg":
        # DCG over score-ranked targets; IDCG over value-sorted targets
        disc = 1.0 / jnp.log2(rank.astype(jnp.float32) + 1.0)
        dcg = seg_sum(jnp.where(in_k, t * disc, 0.0))
        # ideal ordering: sort by (-target) within query
        order2 = jnp.lexsort((-target, indexes))
        s_t2 = target[order2].astype(jnp.float32)
        idcg = seg_sum(jnp.where(in_k, s_t2 * disc, 0.0))
        scores = jnp.where(idcg > 0, dcg / jnp.maximum(idcg, 1e-12), 0.0)
        scores = jnp.clip(scores, 0.0, 1.0)
        return scores, n_pos, valid

    raise ValueError(f"Unknown grouped retrieval metric: {metric}")
