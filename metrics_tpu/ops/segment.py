"""Segment-grouped retrieval kernels.

The TPU-native replacement for the reference's host-side group-by loop
(``retrieval/base.py:113-145``: ``_flexible_bincount(...).cpu().tolist()`` +
``torch.split`` + python loop over queries). Here the whole evaluation is one
device program:

1. one payload-carrying variadic sort groups rows by query and ranks docs by
   score inside each query,
2. segment boundaries come from neighbor comparison; within-segment positions
   and segmented sums come from cumsum/cummax scans (the cummax-base trick,
   sign-split for general values),
3. every per-query retrieval metric is a segmented-scan value read at its
   query's last row — no host round-trips, no ragged splits, no scatters or
   gathers, O(N log N) total and fully jit-compatible with a static row count.

Measured design notes (4.2M docs / 65k queries, v5e, device_get-synced p50 —
``block_until_ready`` does not round-trip on the tunneled backend):

- **Gathers are the enemy, not the sort.** The original
  ``order = lexsort(...); x[order]`` layout cost 305 ms, of which the 2-key sort
  itself was only 38 ms — each 4M-row gather costs ~90 ms on TPU. One
  ``lax.sort`` carrying all three columns as payloads does the same layout in
  45 ms (6.8x).
- **Within-segment positions come from scans, not segment_min+gather**:
  ``cummax(where(new_seg, pos, 0))`` broadcasts each segment's start row to its
  members in one associative scan.
- Net: RetrievalMAP end-to-end went 8.4 -> 22.0 Mdocs/s (the remaining time was
  the sort at ~45 ms + ~4 linear scans/scatters at ~15-25 ms each).
  Experiment grid: experiments/retrieval_exp.py. Round 10 landed that fused
  kernel: :func:`segment_multi_scan` folds every integer statistic into one
  pass (associative_scan tuple carry portable / Pallas streaming on TPU), so
  the post-sort integer scan count is now <= 2 fused passes per metric
  (3 for r_precision's total-gated re-count), down from ~5 global scan pairs.
- **Round 6, the sort's operand bytes** (the bitonic network costs ~passes x
  bytes, see ops/rank.py): the layout sort now carries (indexes, -preds,
  target) only — 12 B/row vs the old 20 (sorted keys come out of ``lax.sort``
  too, so re-carrying indexes/preds as payloads was pure overhead), and ndcg's
  ideal-layout sort recovers targets by negating its own key (8 vs 12 B/row).
  A radix PARTITION-by-query replacement for this sort was evaluated and
  rejected: a materializing partition needs one computed-destination
  gather/scatter per pass (~90 ms per 16M rows measured, vs ~45 ms for the
  whole 4M-row payload sort), and a gather-free partition needs exactly the
  data reorganization the sort already does — grid and verdict in
  experiments/rank_exp.py.
"""

from contextlib import contextmanager
from typing import Iterator, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.histogram import _on_tpu, _provably_unsharded


def _segment_cumsum_nonneg(values: Array, new_seg: Array) -> Array:
    """Within-segment inclusive cumsum for NON-NEGATIVE values.

    The global cumsum is non-decreasing, so each segment's base (global cumsum
    just before the segment) can be broadcast to its rows with one ``cummax``
    instead of a per-row gather. Callers must guarantee ``values >= 0``.

    Dtype-preserving: count-like streams are passed as int32 so the GLOBAL
    running sum stays exact to 2^31 rows (an f32 global sum would lose integer
    exactness past 2^24 positive rows — the scale this module's own 2^24-row
    benchmarks run at). Float streams must NOT use this one-pass form — the
    base-difference loses ulp(global running sum) per segment; they go through
    :func:`_segment_cumsum_float` instead.
    """
    g = jnp.cumsum(values)
    base = jax.lax.cummax(jnp.where(new_seg, g - values, jnp.zeros_like(g)))
    return g - base


def _segment_cumsum_float(values: Array, new_seg: Array, block: int = 2048) -> Array:
    """Within-segment inclusive cumsum for float values with BLOCK-LOCAL precision.

    The cummax-base trick differences two GLOBAL cumsums; for float streams the
    global running sum reaches ``N * mean|v|`` and the difference loses
    ``ulp(global)`` per segment — measured up to 4e-3 per-query error at 2^22
    rows (r5 review finding). Here the array splits into fixed-size blocks:
    in-block segmented cumsums run fully parallel, and the open segment's carry
    across block boundaries — an affine reset-composition — comes from ONE
    ``associative_scan`` over the block summaries (thousands of elements, so
    the recursive decomposition that makes element-level associative_scan
    uncompilable at 2^24 stays trivial). Every intermediate magnitude is
    bounded by ``block * mean|v|`` (or the true segment sum), so the error
    matches plain per-segment summation — measured ~1000x tighter at 2^22 at
    parity throughput (experiments/ndcg_scan_probe.py). Handles ARBITRARY sign
    via an in-block sign-split (block-local magnitudes keep the split benign).
    Integer count streams don't need this — int addition is exact — and stay
    on the global one-pass :func:`_segment_cumsum_nonneg`.
    """
    n = values.shape[0]
    pad = (-n) % block
    if pad:
        values = jnp.concatenate([values, jnp.zeros((pad,), values.dtype)])
        # padding rows start their own segment so they can never extend a carry
        new_seg = jnp.concatenate([new_seg, jnp.ones((pad,), bool)])
    nb = values.shape[0] // block
    v_blocks = values.reshape(nb, block)
    s_blocks = new_seg.reshape(nb, block)

    def inblock(vb, sb):
        g = jnp.cumsum(vb, axis=1)
        base = jax.lax.cummax(jnp.where(sb, g - vb, jnp.zeros_like(g)), axis=1)
        return g - base

    # pass 1, fully parallel over blocks: in-block segmented cumsum
    within = inblock(jnp.maximum(v_blocks, 0.0), s_blocks) - inblock(jnp.maximum(-v_blocks, 0.0), s_blocks)

    # pass 2: the carry into each block. The recurrence
    #   c_{i+1} = has_boundary_i ? within_last_i : c_i + within_last_i
    # is the affine reset-composition f_i(c) = m_i*c + a_i with m_i = !has_b_i,
    # a_i = within_last_i — associative, so one associative_scan over the nb
    # BLOCK SUMMARIES (thousands, not 2^24 rows: compile stays trivial) replaces
    # a sequential lax.scan that measured ~45% of the kernel at 2^24.
    has_b = s_blocks.any(axis=1)
    m = (~has_b).astype(values.dtype)
    a = within[:, -1]

    def compose(f, g):  # g after f: (m, a) pairs
        return (g[0] * f[0], g[0] * f[1] + g[1])

    _, cum_a = jax.lax.associative_scan(compose, (m, a))
    carry = jnp.concatenate([jnp.zeros((1,), values.dtype), cum_a[:-1]])

    # pass 3, fully parallel: rows before a block's first boundary extend the
    # carried-in segment
    before_first = jnp.cumsum(s_blocks, axis=1) == 0
    out = within + jnp.where(before_first, carry[:, None], 0.0)
    return out.reshape(-1)[:n]


def _segment_suffix_sum_nonneg(values: Array, is_last: Array) -> Array:
    """Within-segment inclusive SUFFIX sum for non-negative values.

    The reversed array's segment boundaries are the reversed ``is_last`` mask,
    so one reversed prefix scan gives each row the sum of the rows at-or-after
    it inside its segment — this broadcasts a segment total to every row
    (``prefix + suffix - value``) without the per-row gather the old
    r_precision path needed.
    """
    rev = lambda x: x[::-1]
    return rev(_segment_cumsum_nonneg(rev(values), rev(is_last)))


# ------------------------------------------------------ fused segmented multi-scan
#
# Round 10: every retrieval/curve compute used to issue one GLOBAL scan pair per
# statistic (~5 cumsum/cummax passes post-sort, each a full read+write of the
# sorted rows). ``segment_multi_scan`` computes ALL the integer per-segment
# running statistics behind one entry point with three tiers:
#
# - **Pallas (TPU, n >= SEGSCAN_PALLAS_MIN_SIZE)** — the tier the fusion exists
#   for: streams blocks through VMEM, runs a flag-aware Hillis-Steele doubling
#   scan in-register, carries the open segment across blocks in scratch — ONE
#   HBM read + one write for all k statistics.
# - **assoc** — a single ``lax.associative_scan`` over a tuple carry under the
#   segmented monoid  (fa, a) ⊕ (fb, b) = (fa | fb,  fb ? b : op(a, b)).
#   Fully general (min/max lanes over arbitrary flags) but costs ~0.7 s of XLA
#   compile PER JITTED SHAPE on CPU (~5 s at 2^24; probe in
#   experiments/segment_fused_probe.py) — fine for a warm serving process
#   (excache pays it once), hostile to multi-shape cold paths and CI.
# - **native** — per-lane ``cumsum``/``cummax``/``cummin`` XLA scan primitives:
#   sum lanes via the sign-split cummax-base trick, and any op when the caller
#   statically declares ONE global segment (``new_seg=None``). Compiles in
#   milliseconds; the off-TPU default whenever it applies.
#
# Int sums/mins/maxes are exact under any association, so all tiers are
# bit-identical to the unfused scans. The 2^24-row associative_scan
# compile-time rejection recorded above applied to the per-element FLOAT scan
# variants tried in round 5 on the tunneled v5e backend. Float streams keep
# :func:`_segment_cumsum_float` (precision contract).

#: Below this row count the associative_scan tier wins (kernel launch + padding
#: overheads dominate); mirrors histogram.py's PALLAS_MIN_SIZE.
SEGSCAN_PALLAS_MIN_SIZE = 1 << 18
#: Pallas block width: a lane multiple; log2(block) doubling steps in-register.
SEGSCAN_BLOCK = 1024

_SCAN_OPS = ("sum", "min", "max")
_FORCED_SCAN_IMPL: Optional[str] = None


@contextmanager
def force_scan_impl(impl: Optional[str]) -> Iterator[None]:
    """Pin the multi-scan tier: ``"native"`` (per-lane cumsum/cummax XLA scans —
    sum ops or a single global segment only), ``"assoc"``, ``"pallas"``,
    ``"pallas_interpret"`` (the TPU kernel under the Pallas interpreter — how
    CPU CI exercises it), or None to restore auto dispatch."""
    global _FORCED_SCAN_IMPL
    if impl not in (None, "native", "assoc", "pallas", "pallas_interpret"):
        raise ValueError(f"unknown segment scan impl: {impl!r}")
    prev = _FORCED_SCAN_IMPL
    _FORCED_SCAN_IMPL = impl
    try:
        yield
    finally:
        _FORCED_SCAN_IMPL = prev


def _scan_identity(op: str, dtype) -> Array:
    if op == "sum":
        return jnp.zeros((), dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if op == "min" else info.min, dtype)


def _scan_combine(op: str, a: Array, b: Array) -> Array:
    if op == "sum":
        return a + b
    if op == "min":
        return jnp.minimum(a, b)
    return jnp.maximum(a, b)


def _multi_scan_native_sum(values: Tuple[Array, ...], flags: Array) -> Tuple[Array, ...]:
    """Native tier, sum lanes: one cummax-base segmented cumsum per lane.

    ``jnp.cumsum``/``lax.cummax`` are first-class XLA scan primitives — they
    compile in milliseconds where the tuple-carry ``associative_scan`` costs
    ~0.7 s PER JITTED SHAPE (measured on CPU jaxlib; the recursive odd/even
    decomposition emits hundreds of slice/concat ops XLA must re-optimize every
    compile). Serving pays compile once through excache, but the test suite and
    any cold multi-shape client pay it per shape — so sum-only requests (the
    dominant case: rank/count/gated-count lanes) take this tier by default off
    TPU. The sign-split keeps :func:`_segment_cumsum_nonneg`'s non-negativity
    precondition honest for arbitrary int lanes; int addition is exact, so the
    result is bit-identical to the fused carry.
    """
    out = []
    for v in values:
        pos = _segment_cumsum_nonneg(jnp.maximum(v, 0), flags)
        neg = _segment_cumsum_nonneg(jnp.maximum(-v, 0), flags)
        out.append((pos - neg).astype(v.dtype))
    return tuple(out)


def _multi_scan_native_global(values: Tuple[Array, ...], ops: Tuple[str, ...]) -> Tuple[Array, ...]:
    """Native tier, single-global-segment requests (``new_seg=None``): every op
    — min/max included — is one plain XLA scan, no segmented monoid needed."""
    out = []
    for op, v in zip(ops, values):
        if op == "sum":
            out.append(jnp.cumsum(v).astype(v.dtype))
        elif op == "min":
            out.append(jax.lax.cummin(v))
        else:
            out.append(jax.lax.cummax(v))
    return tuple(out)


def _multi_scan_assoc(values: Tuple[Array, ...], flags: Array, ops: Tuple[str, ...]) -> Tuple[Array, ...]:
    """Portable tier: ONE ``associative_scan`` with a (flag, *stats) tuple carry."""

    def combine(a, b):
        af, bf = a[0], b[0]
        out = [af | bf]
        for op, av, bv in zip(ops, a[1:], b[1:]):
            out.append(jnp.where(bf, bv, _scan_combine(op, av, bv)))
        return tuple(out)

    res = jax.lax.associative_scan(combine, (flags,) + tuple(values))
    return tuple(res[1:])


def _multi_scan_pallas(
    values: Tuple[Array, ...], flags: Array, ops: Tuple[str, ...], interpret: bool = False
) -> Tuple[Array, ...]:
    """TPU tier: blocked streaming kernel, carry in scratch across a sequential grid.

    Each grid step loads one ``(1, SEGSCAN_BLOCK)`` block per statistic, runs a
    flag-aware Hillis-Steele doubling scan (log2(block) vector steps — handles
    sum/min/max and negative values uniformly, no cummax-base trick needed),
    splices the carried-in open segment onto rows before the block's first
    boundary, and writes the next carry (the block's last row) back to scratch.
    One pass over HBM for all k statistics. ``interpret=True`` runs the same
    kernel under the Pallas interpreter (CPU tests; tracer-identical program).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    k = len(values)
    n = values[0].shape[0]
    block = SEGSCAN_BLOCK
    pad = (-n) % block
    f_i = flags.astype(jnp.int32)
    if pad:
        # padding rows open their own segments with identity values: they can
        # never extend a carry, and outputs past n are sliced away
        f_i = jnp.concatenate([f_i, jnp.ones((pad,), jnp.int32)])
        values = tuple(
            jnp.concatenate([v, jnp.full((pad,), _scan_identity(op, v.dtype), v.dtype)])
            for op, v in zip(ops, values)
        )
    m = n + pad
    grid = m // block
    v2 = tuple(v.reshape(grid, block) for v in values)
    f2 = f_i.reshape(grid, block)

    def kernel(*refs):
        v_refs, f_ref = refs[:k], refs[k]
        o_refs, c_refs = refs[k + 1 : 2 * k + 1], refs[2 * k + 1 :]

        @pl.when(pl.program_id(0) == 0)
        def _init():
            for j, op in enumerate(ops):
                c_refs[j][0, 0] = _scan_identity(op, v_refs[j].dtype)

        f_in = f_ref[...] != 0
        idx = jax.lax.broadcasted_iota(jnp.int32, (1, block), 1)
        vals = [r[...] for r in v_refs]
        f = f_in
        d = 1
        while d < block:  # static: unrolled log2(block) doubling steps
            f_prev = jnp.where(idx < d, True, jnp.roll(f, d, axis=1))
            vals = [
                jnp.where(
                    f,
                    v,
                    _scan_combine(
                        op, jnp.where(idx < d, _scan_identity(op, v.dtype), jnp.roll(v, d, axis=1)), v
                    ),
                )
                for op, v in zip(ops, vals)
            ]
            f = f | f_prev
            d *= 2
        # rows before the block's first boundary extend the carried-in segment
        before_first = jnp.cumsum(f_in.astype(jnp.int32), axis=1) == 0
        for j, (op, v) in enumerate(zip(ops, vals)):
            out = jnp.where(before_first, _scan_combine(op, c_refs[j][0, 0], v), v)
            o_refs[j][...] = out
            c_refs[j][0, 0] = out[0, block - 1]

    spec = pl.BlockSpec((1, block), lambda i: (i, 0))
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec] * (k + 1),
        out_specs=[spec] * k,
        out_shape=[jax.ShapeDtypeStruct((grid, block), v.dtype) for v in values],
        scratch_shapes=[pltpu.VMEM((1, 1), v.dtype) for v in values],
        interpret=interpret,
    )(*v2, f2)
    return tuple(o.reshape(-1)[:n] for o in outs)


def segment_multi_scan(
    values: Sequence[Array],
    new_seg: Optional[Array],
    *,
    ops: Optional[Sequence[str]] = None,
    reverse: bool = False,
) -> Tuple[Array, ...]:
    """All per-segment inclusive running statistics in ONE pass over sorted rows.

    ``values`` is a tuple of equal-length INTEGER arrays; ``ops`` names the
    per-array reduction (``"sum"`` default, ``"min"``, ``"max"``). ``new_seg``
    marks segment-start rows (forward) — with ``reverse=True`` it marks segment
    LAST rows and the result is the within-segment inclusive SUFFIX statistic
    (the fused replacement for the flip-scan-flip suffix helpers). Pass
    ``new_seg=None`` to declare ONE GLOBAL segment statically — a global
    running statistic (e.g. the curve tail's suffix-min) that no runtime flag
    column can promise at trace time. A position-in-segment / rank column is a
    ``"sum"`` over ones; a segment-start broadcast is ``pos - rank + 1``.

    Integer-only by contract: int add/min/max are exact under any association,
    so every tier — the native per-lane XLA scans, the ``associative_scan``
    tuple-carry portable form, the Pallas TPU kernel, and the legacy
    per-statistic global scans — produces bit-identical results
    (property-tested across the adversarial suite in
    tests/unittests/classification/test_segment_multi_scan.py). Float streams
    must keep :func:`_segment_cumsum_float`'s blocked form instead.

    Dispatch: TPU + provably-unsharded + n >= ``SEGSCAN_PALLAS_MIN_SIZE`` takes
    the Pallas kernel (ONE fused HBM pass for k lanes — the tier the fusion
    exists for); otherwise sum-only or ``new_seg=None`` requests take the
    native per-lane scans (milliseconds to compile vs ~0.7 s/shape for the
    tuple carry — see :func:`_multi_scan_native_sum`), and only min/max lanes
    over real segment flags need the ``associative_scan`` tuple carry.
    :func:`force_scan_impl` pins a tier for tests.
    """
    values = tuple(jnp.asarray(v) for v in values)
    if not values:
        raise ValueError("segment_multi_scan needs at least one values array")
    ops = ("sum",) * len(values) if ops is None else tuple(ops)
    if len(ops) != len(values):
        raise ValueError(f"got {len(values)} values arrays but {len(ops)} ops")
    for op, v in zip(ops, values):
        if op not in _SCAN_OPS:
            raise ValueError(f"unknown scan op {op!r}; expected one of {_SCAN_OPS}")
        if not jnp.issubdtype(v.dtype, jnp.integer):
            raise ValueError(
                f"segment_multi_scan is integer-only (exact under reassociation); got {v.dtype}. "
                "Float streams go through _segment_cumsum_float."
            )
    global_seg = new_seg is None
    flags = None if global_seg else jnp.asarray(new_seg)
    if reverse:
        values = tuple(v[::-1] for v in values)
        if flags is not None:
            flags = flags[::-1]
    sum_only = all(op == "sum" for op in ops)
    impl = _FORCED_SCAN_IMPL
    if impl is None:
        x = values[0]
        if x.shape[0] >= SEGSCAN_PALLAS_MIN_SIZE and _on_tpu(x) and _provably_unsharded(x):
            impl = "pallas"
        elif global_seg or sum_only:
            impl = "native"
        else:
            impl = "assoc"
    if impl == "native":
        if global_seg:
            outs = _multi_scan_native_global(values, ops)
        elif sum_only:
            outs = _multi_scan_native_sum(values, flags)
        else:
            raise ValueError(
                "the native tier covers sum lanes (or any op with new_seg=None); "
                "min/max over real segment flags need the assoc or pallas tier"
            )
    else:
        if flags is None:
            # materialize the static single-segment claim for the generic tiers
            flags = jnp.zeros((values[0].shape[0],), bool).at[0].set(True)
        if impl == "assoc":
            outs = _multi_scan_assoc(values, flags, ops)
        else:
            outs = _multi_scan_pallas(values, flags, ops, interpret=(impl == "pallas_interpret"))
    if reverse:
        outs = tuple(o[::-1] for o in outs)
    return outs


# every retrieval metric's per-query value is a segmented-scan read at the
# segment's last row: the whole family runs with ZERO segment scatters
# (one or two payload sorts + a handful of cumsum/cummax scans + plain sums)
_SCAN_METRICS = frozenset(
    {
        "average_precision", "reciprocal_rank", "precision", "recall", "hit_rate",
        "fall_out", "ndcg", "r_precision",
    }
)


def _scan_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int],
    adaptive_k: bool,
) -> Tuple[Array, Array, Array]:
    """Scan-only fast path: per-query score materialized at each segment's LAST
    row; other rows carry 0 / valid=False. The caller's reduction is elementwise
    so row-aligned results are interchangeable with segment-aligned ones.

    Why: ``segment_sum`` (a scatter) costs ~174 ms per call at 2^24 rows on v5e
    even with sorted indices, while ``cumsum``/``cummax`` scans cost ~30 ms; AP
    needs 4+ per-segment reductions. Expressing each as "segmented cumsum value
    at the last row" removes every scatter: 715 -> ~300 ms for the full AP
    kernel at 2^24. Since round 10 the integer statistics ride ONE fused
    multi-scan carry (:func:`segment_multi_scan`) instead of a cumsum+cummax
    scan pair per statistic; a second fused pass exists only where a statistic
    is GATED on the first pass's rank (top_k masks, r_precision's total).
    """
    n = indexes.shape[0]
    # the sorted KEYS come out of lax.sort too: carrying (indexes, preds) again
    # as payloads (the round-3 layout) moved 20 B/row through the ~300-pass
    # bitonic network where 12 B/row suffice — s_idx IS the sorted key column,
    # and the pred VALUES are never consumed post-ranking (only their order)
    s_idx, _, s_target = jax.lax.sort((indexes, -preds, target), num_keys=2, is_stable=True)
    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    is_last = jnp.concatenate([new_seg[1:], jnp.ones(1, dtype=bool)])
    pos = jnp.arange(n)

    # counts run in int32 through the fused segmented scan: exact to 2^31 rows
    # (f32 would drift past 2^24 positive rows); cast at the read points
    binary_i = (s_target > 0).astype(jnp.int32)
    binary_t = binary_i.astype(jnp.float32)
    big = jnp.int32(2**31 - 1)

    # ---- fused pass A (ONE scan): every statistic that does not depend on the
    # within-segment rank rides the same tuple carry — rank itself (a segmented
    # sum of ones), the ungated relevant/non-relevant counts, and
    # reciprocal_rank's first-relevant position (a segmented min). The old path
    # issued one cumsum+cummax scan pair PER statistic (~5 global passes).
    a_vals = [jnp.ones((n,), jnp.int32)]
    a_ops = ["sum"]
    if metric == "fall_out":
        nonrel = 1 - binary_i
        a_vals.append(nonrel)
        a_ops.append("sum")
    else:
        a_vals.append(binary_i)
        a_ops.append("sum")
    if metric == "reciprocal_rank":
        # 1-based global position of the segment's first relevant row: read at
        # last rows of segments that HAVE one (n_pos > 0), where the segmented
        # min equals the old global-cummax marker value bit-for-bit
        a_vals.append(jnp.where(binary_i > 0, pos.astype(jnp.int32) + 1, big))
        a_ops.append("min")
    a_out = segment_multi_scan(tuple(a_vals), new_seg, ops=tuple(a_ops))
    rank = a_out[0]  # 1-based position within its segment
    in_k = jnp.ones(n, dtype=bool) if top_k is None else rank <= top_k

    cum_rel_i = None if metric == "fall_out" else a_out[1]
    cum_rel = None if cum_rel_i is None else cum_rel_i.astype(jnp.float32)
    if metric != "fall_out":
        if top_k is None:
            cum_rel_k = cum_rel
        elif metric in ("average_precision", "precision", "recall", "hit_rate"):
            # ---- fused pass B: the rank-gated count (depends on pass A's rank,
            # so it cannot share its carry — a real data dependency, not a
            # missed fusion). ndcg/reciprocal_rank never consume it.
            (cum_rel_k_i,) = segment_multi_scan((binary_i * in_k.astype(jnp.int32),), new_seg)
            cum_rel_k = cum_rel_k_i.astype(jnp.float32)
        else:
            cum_rel_k = None
        n_pos = jnp.where(is_last, cum_rel, 0.0)
    valid = is_last & (s_idx >= 0)

    if metric == "fall_out":
        cum_nonrel = a_out[1].astype(jnp.float32)
        if top_k is None:
            cum_nonrel_k = cum_nonrel
        else:
            (cum_nonrel_k_i,) = segment_multi_scan((nonrel * in_k.astype(jnp.int32),), new_seg)
            cum_nonrel_k = cum_nonrel_k_i.astype(jnp.float32)
        n_neg = jnp.where(is_last, cum_nonrel, 0.0)
        scores = jnp.where(is_last & (n_neg > 0), cum_nonrel_k / jnp.maximum(n_neg, 1.0), 0.0)
        return scores, n_neg, valid  # n_positive slot carries negatives for empty handling

    if metric == "average_precision":
        contrib = jnp.where(in_k, binary_t * cum_rel_k / rank, 0.0)
        # fractional stream: blocked scan keeps the precision segment-local
        cum_contrib = _segment_cumsum_float(contrib, new_seg)
        scores = jnp.where(is_last & (cum_rel_k > 0), cum_contrib / jnp.maximum(cum_rel_k, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "reciprocal_rank":
        # rank of the first relevant row = its global position relative to the
        # segment start, recovered from pass A as pos - rank + 1
        first_rel_pos = a_out[2]
        seg_start_row = pos - rank + 1
        first_rel_rank = (first_rel_pos - 1 - seg_start_row + 1).astype(jnp.float32)
        scores = jnp.where(is_last & (n_pos > 0), 1.0 / jnp.maximum(first_rel_rank, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "ndcg":
        # DCG over score-ranked targets; IDCG over value-sorted targets. Both
        # sorts are query-major with identical segment spans, so the positional
        # rank/in_k/discount arrays apply unchanged to the ideal layout.
        t_float = s_target.astype(jnp.float32)
        disc = 1.0 / jnp.log2(rank.astype(jnp.float32) + 1.0)
        cum_dcg = _segment_cumsum_float(jnp.where(in_k, t_float * disc, 0.0), new_seg)
        # ideal layout: recover the sorted targets by negating the sorted KEY
        # (sign-flip is an exact involution) instead of carrying them again
        _, neg_t2 = jax.lax.sort((indexes, -target), num_keys=2, is_stable=True)
        s_t2 = -neg_t2
        cum_idcg = _segment_cumsum_float(jnp.where(in_k, s_t2.astype(jnp.float32) * disc, 0.0), new_seg)
        idcg = jnp.where(is_last, cum_idcg, 0.0)
        scores = jnp.where(
            is_last & (idcg > 0), jnp.clip(cum_dcg / jnp.maximum(idcg, 1e-12), 0.0, 1.0), 0.0
        )
        return scores, n_pos, valid

    if metric == "r_precision":
        # relevant among the top-(n_pos) ranked docs; the segment's positive
        # total reaches every row as prefix + suffix - value (pass A already
        # carries the prefix; one fused reverse pass adds the suffix), not the
        # per-row gather the old segment-reduction path needed. The gated
        # re-count is a third pass — the gate depends on the total, a true
        # data dependency unique to this metric.
        (suffix,) = segment_multi_scan((binary_i,), is_last, reverse=True)
        total = (cum_rel_i + suffix - binary_i).astype(jnp.float32)
        in_r = rank.astype(jnp.float32) <= total
        (rel_in_r_i,) = segment_multi_scan((binary_i * in_r.astype(jnp.int32),), new_seg)
        rel_in_r = rel_in_r_i.astype(jnp.float32)
        scores = jnp.where(is_last & (n_pos > 0), rel_in_r / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    count_f = rank.astype(jnp.float32)  # at last row == segment size
    if top_k is None:
        k_per_seg = count_f
    elif adaptive_k:
        k_per_seg = jnp.minimum(float(top_k), count_f)
    else:
        k_per_seg = jnp.full_like(count_f, float(top_k))

    if metric == "precision":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(k_per_seg, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "recall":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "hit_rate":
        scores = jnp.where(is_last & (cum_rel_k > 0), 1.0, 0.0)
        return scores, n_pos, valid

    raise ValueError(f"Metric {metric} is not scan-friendly")


def grouped_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int] = None,
    adaptive_k: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-query scores for every query in one fused device pass.

    Returns ``(scores, n_positive, valid)`` each of length N (the padded row
    count, an upper bound on the number of queries); only entries where
    ``valid`` is True are real queries. ``n_positive`` is the per-query count of
    positive targets (used by the caller for ``empty_target_action`` handling;
    for ``fall_out`` it counts negatives).

    ALIGNMENT CONTRACT: results are ROW-aligned — a query's
    score/n_positive/valid live at its LAST row in (query, -score) sort order,
    every other row holds 0 / False. Consumption must be position-agnostic
    (masked reductions over ``valid``, e.g. ``scores.sum() / valid.sum()``);
    do NOT slice a prefix (``scores[:n_queries]``).

    Every metric takes the scatter-free scan path since round 5: ndcg's
    possibly-negative float gains run through the blocked segmented cumsum
    (:func:`_segment_cumsum_float`) and r_precision's segment-total broadcast is a
    prefix+suffix scan pair, so the 174 ms-per-call ``segment_sum`` scatters at
    2^24 rows (and the one per-row gather r_precision kept) are gone entirely.
    """
    if metric not in _SCAN_METRICS:
        raise ValueError(f"Unknown grouped retrieval metric: {metric}")
    return _scan_retrieval_scores(indexes, preds, target, metric, top_k, adaptive_k)
