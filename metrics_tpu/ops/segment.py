"""Segment-grouped retrieval kernels.

The TPU-native replacement for the reference's host-side group-by loop
(``retrieval/base.py:113-145``: ``_flexible_bincount(...).cpu().tolist()`` +
``torch.split`` + python loop over queries). Here the whole evaluation is one
device program:

1. one payload-carrying variadic sort groups rows by query and ranks docs by
   score inside each query,
2. segment boundaries come from neighbor comparison; within-segment positions
   and segmented sums come from cumsum/cummax scans (the cummax-base trick,
   sign-split for general values),
3. every per-query retrieval metric is a segmented-scan value read at its
   query's last row — no host round-trips, no ragged splits, no scatters or
   gathers, O(N log N) total and fully jit-compatible with a static row count.

Measured design notes (4.2M docs / 65k queries, v5e, device_get-synced p50 —
``block_until_ready`` does not round-trip on the tunneled backend):

- **Gathers are the enemy, not the sort.** The original
  ``order = lexsort(...); x[order]`` layout cost 305 ms, of which the 2-key sort
  itself was only 38 ms — each 4M-row gather costs ~90 ms on TPU. One
  ``lax.sort`` carrying all three columns as payloads does the same layout in
  45 ms (6.8x).
- **Within-segment positions come from scans, not segment_min+gather**:
  ``cummax(where(new_seg, pos, 0))`` broadcasts each segment's start row to its
  members in one associative scan.
- Net: RetrievalMAP end-to-end went 8.4 -> 22.0 Mdocs/s (the remaining time is
  the sort at ~45 ms + ~4 linear scans/scatters at ~15-25 ms each; a fused
  one-pass segmented scan would need a hand-written kernel for <2x more).
  Experiment grid: experiments/retrieval_exp.py.
- **Round 6, the sort's operand bytes** (the bitonic network costs ~passes x
  bytes, see ops/rank.py): the layout sort now carries (indexes, -preds,
  target) only — 12 B/row vs the old 20 (sorted keys come out of ``lax.sort``
  too, so re-carrying indexes/preds as payloads was pure overhead), and ndcg's
  ideal-layout sort recovers targets by negating its own key (8 vs 12 B/row).
  A radix PARTITION-by-query replacement for this sort was evaluated and
  rejected: a materializing partition needs one computed-destination
  gather/scatter per pass (~90 ms per 16M rows measured, vs ~45 ms for the
  whole 4M-row payload sort), and a gather-free partition needs exactly the
  data reorganization the sort already does — grid and verdict in
  experiments/rank_exp.py.
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array


def _segment_cumsum_nonneg(values: Array, new_seg: Array) -> Array:
    """Within-segment inclusive cumsum for NON-NEGATIVE values.

    The global cumsum is non-decreasing, so each segment's base (global cumsum
    just before the segment) can be broadcast to its rows with one ``cummax``
    instead of a per-row gather. Callers must guarantee ``values >= 0``.

    Dtype-preserving: count-like streams are passed as int32 so the GLOBAL
    running sum stays exact to 2^31 rows (an f32 global sum would lose integer
    exactness past 2^24 positive rows — the scale this module's own 2^24-row
    benchmarks run at). Float streams must NOT use this one-pass form — the
    base-difference loses ulp(global running sum) per segment; they go through
    :func:`_segment_cumsum_float` instead.
    """
    g = jnp.cumsum(values)
    base = jax.lax.cummax(jnp.where(new_seg, g - values, jnp.zeros_like(g)))
    return g - base


def _segment_cumsum_float(values: Array, new_seg: Array, block: int = 2048) -> Array:
    """Within-segment inclusive cumsum for float values with BLOCK-LOCAL precision.

    The cummax-base trick differences two GLOBAL cumsums; for float streams the
    global running sum reaches ``N * mean|v|`` and the difference loses
    ``ulp(global)`` per segment — measured up to 4e-3 per-query error at 2^22
    rows (r5 review finding). Here the array splits into fixed-size blocks:
    in-block segmented cumsums run fully parallel, and the open segment's carry
    across block boundaries — an affine reset-composition — comes from ONE
    ``associative_scan`` over the block summaries (thousands of elements, so
    the recursive decomposition that makes element-level associative_scan
    uncompilable at 2^24 stays trivial). Every intermediate magnitude is
    bounded by ``block * mean|v|`` (or the true segment sum), so the error
    matches plain per-segment summation — measured ~1000x tighter at 2^22 at
    parity throughput (experiments/ndcg_scan_probe.py). Handles ARBITRARY sign
    via an in-block sign-split (block-local magnitudes keep the split benign).
    Integer count streams don't need this — int addition is exact — and stay
    on the global one-pass :func:`_segment_cumsum_nonneg`.
    """
    n = values.shape[0]
    pad = (-n) % block
    if pad:
        values = jnp.concatenate([values, jnp.zeros((pad,), values.dtype)])
        # padding rows start their own segment so they can never extend a carry
        new_seg = jnp.concatenate([new_seg, jnp.ones((pad,), bool)])
    nb = values.shape[0] // block
    v_blocks = values.reshape(nb, block)
    s_blocks = new_seg.reshape(nb, block)

    def inblock(vb, sb):
        g = jnp.cumsum(vb, axis=1)
        base = jax.lax.cummax(jnp.where(sb, g - vb, jnp.zeros_like(g)), axis=1)
        return g - base

    # pass 1, fully parallel over blocks: in-block segmented cumsum
    within = inblock(jnp.maximum(v_blocks, 0.0), s_blocks) - inblock(jnp.maximum(-v_blocks, 0.0), s_blocks)

    # pass 2: the carry into each block. The recurrence
    #   c_{i+1} = has_boundary_i ? within_last_i : c_i + within_last_i
    # is the affine reset-composition f_i(c) = m_i*c + a_i with m_i = !has_b_i,
    # a_i = within_last_i — associative, so one associative_scan over the nb
    # BLOCK SUMMARIES (thousands, not 2^24 rows: compile stays trivial) replaces
    # a sequential lax.scan that measured ~45% of the kernel at 2^24.
    has_b = s_blocks.any(axis=1)
    m = (~has_b).astype(values.dtype)
    a = within[:, -1]

    def compose(f, g):  # g after f: (m, a) pairs
        return (g[0] * f[0], g[0] * f[1] + g[1])

    _, cum_a = jax.lax.associative_scan(compose, (m, a))
    carry = jnp.concatenate([jnp.zeros((1,), values.dtype), cum_a[:-1]])

    # pass 3, fully parallel: rows before a block's first boundary extend the
    # carried-in segment
    before_first = jnp.cumsum(s_blocks, axis=1) == 0
    out = within + jnp.where(before_first, carry[:, None], 0.0)
    return out.reshape(-1)[:n]


def _segment_suffix_sum_nonneg(values: Array, is_last: Array) -> Array:
    """Within-segment inclusive SUFFIX sum for non-negative values.

    The reversed array's segment boundaries are the reversed ``is_last`` mask,
    so one reversed prefix scan gives each row the sum of the rows at-or-after
    it inside its segment — this broadcasts a segment total to every row
    (``prefix + suffix - value``) without the per-row gather the old
    r_precision path needed.
    """
    rev = lambda x: x[::-1]
    return rev(_segment_cumsum_nonneg(rev(values), rev(is_last)))


# every retrieval metric's per-query value is a segmented-scan read at the
# segment's last row: the whole family runs with ZERO segment scatters
# (one or two payload sorts + a handful of cumsum/cummax scans + plain sums)
_SCAN_METRICS = frozenset(
    {
        "average_precision", "reciprocal_rank", "precision", "recall", "hit_rate",
        "fall_out", "ndcg", "r_precision",
    }
)


def _scan_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int],
    adaptive_k: bool,
) -> Tuple[Array, Array, Array]:
    """Scan-only fast path: per-query score materialized at each segment's LAST
    row; other rows carry 0 / valid=False. The caller's reduction is elementwise
    so row-aligned results are interchangeable with segment-aligned ones.

    Why: ``segment_sum`` (a scatter) costs ~174 ms per call at 2^24 rows on v5e
    even with sorted indices, while ``cumsum``/``cummax`` scans cost ~30 ms; AP
    needs 4+ per-segment reductions. Expressing each as "segmented cumsum value
    at the last row" (base broadcast by ``cummax`` — exact for the non-negative
    summands used here) removes every scatter: 715 -> ~300 ms for the full AP
    kernel at 2^24. (``lax.associative_scan`` segmented scans were rejected:
    the recursive decomposition takes minutes to compile at this size.)
    """
    n = indexes.shape[0]
    # the sorted KEYS come out of lax.sort too: carrying (indexes, preds) again
    # as payloads (the round-3 layout) moved 20 B/row through the ~300-pass
    # bitonic network where 12 B/row suffice — s_idx IS the sorted key column,
    # and the pred VALUES are never consumed post-ranking (only their order)
    s_idx, _, s_target = jax.lax.sort((indexes, -preds, target), num_keys=2, is_stable=True)
    new_seg = jnp.concatenate([jnp.ones(1, dtype=bool), s_idx[1:] != s_idx[:-1]])
    is_last = jnp.concatenate([new_seg[1:], jnp.ones(1, dtype=bool)])
    pos = jnp.arange(n)
    seg_start_row = jax.lax.cummax(jnp.where(new_seg, pos, 0))
    rank = pos - seg_start_row + 1

    # counts run in int32 through the cumsum-base trick: exact to 2^31 rows
    # (f32 would drift past 2^24 positive rows); cast at the read points
    binary_i = (s_target > 0).astype(jnp.int32)
    binary_t = binary_i.astype(jnp.float32)
    in_k = jnp.ones(n, dtype=bool) if top_k is None else rank <= top_k
    in_k_i = in_k.astype(jnp.int32)

    def segcumsum(v):  # within-segment cumsum, v >= 0 (see _segment_cumsum_nonneg)
        return _segment_cumsum_nonneg(v, new_seg)

    cum_rel_k = segcumsum(binary_i * in_k_i).astype(jnp.float32)
    cum_rel = cum_rel_k if top_k is None else segcumsum(binary_i).astype(jnp.float32)
    n_pos = jnp.where(is_last, cum_rel, 0.0)
    valid = is_last & (s_idx >= 0)

    if metric == "fall_out":
        nonrel = 1 - binary_i
        cum_nonrel_k = segcumsum(nonrel * in_k_i).astype(jnp.float32)
        cum_nonrel = cum_nonrel_k if top_k is None else segcumsum(nonrel).astype(jnp.float32)
        n_neg = jnp.where(is_last, cum_nonrel, 0.0)
        scores = jnp.where(is_last & (n_neg > 0), cum_nonrel_k / jnp.maximum(n_neg, 1.0), 0.0)
        return scores, n_neg, valid  # n_positive slot carries negatives for empty handling

    if metric == "average_precision":
        contrib = jnp.where(in_k, binary_t * cum_rel_k / rank, 0.0)
        # fractional stream: blocked scan keeps the precision segment-local
        cum_contrib = _segment_cumsum_float(contrib, new_seg)
        scores = jnp.where(is_last & (cum_rel_k > 0), cum_contrib / jnp.maximum(cum_rel_k, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "reciprocal_rank":
        # global cummax of "position+1 of each segment's first relevant row":
        # later segments' markers dominate earlier ones, and the value is only
        # read at last rows of segments that HAVE a relevant row (n_pos > 0)
        marker = jnp.where((binary_t > 0) & (cum_rel == 1), pos + 1, 0)
        first_rel_pos = jax.lax.cummax(marker)
        first_rel_rank = (first_rel_pos - 1 - seg_start_row + 1).astype(jnp.float32)
        scores = jnp.where(is_last & (n_pos > 0), 1.0 / jnp.maximum(first_rel_rank, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "ndcg":
        # DCG over score-ranked targets; IDCG over value-sorted targets. Both
        # sorts are query-major with identical segment spans, so the positional
        # rank/in_k/discount arrays apply unchanged to the ideal layout.
        t_float = s_target.astype(jnp.float32)
        disc = 1.0 / jnp.log2(rank.astype(jnp.float32) + 1.0)
        cum_dcg = _segment_cumsum_float(jnp.where(in_k, t_float * disc, 0.0), new_seg)
        # ideal layout: recover the sorted targets by negating the sorted KEY
        # (sign-flip is an exact involution) instead of carrying them again
        _, neg_t2 = jax.lax.sort((indexes, -target), num_keys=2, is_stable=True)
        s_t2 = -neg_t2
        cum_idcg = _segment_cumsum_float(jnp.where(in_k, s_t2.astype(jnp.float32) * disc, 0.0), new_seg)
        idcg = jnp.where(is_last, cum_idcg, 0.0)
        scores = jnp.where(
            is_last & (idcg > 0), jnp.clip(cum_dcg / jnp.maximum(idcg, 1e-12), 0.0, 1.0), 0.0
        )
        return scores, n_pos, valid

    if metric == "r_precision":
        # relevant among the top-(n_pos) ranked docs; the segment's positive
        # total reaches every row as prefix + suffix - value (two scans), not
        # the per-row gather the old segment-reduction path needed
        suffix = _segment_suffix_sum_nonneg(binary_i, is_last)
        total = (segcumsum(binary_i) + suffix - binary_i).astype(jnp.float32)
        in_r = rank.astype(jnp.float32) <= total
        rel_in_r = segcumsum(binary_i * in_r.astype(jnp.int32)).astype(jnp.float32)
        scores = jnp.where(is_last & (n_pos > 0), rel_in_r / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    count_f = rank.astype(jnp.float32)  # at last row == segment size
    if top_k is None:
        k_per_seg = count_f
    elif adaptive_k:
        k_per_seg = jnp.minimum(float(top_k), count_f)
    else:
        k_per_seg = jnp.full_like(count_f, float(top_k))

    if metric == "precision":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(k_per_seg, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "recall":
        scores = jnp.where(is_last & (n_pos > 0), cum_rel_k / jnp.maximum(n_pos, 1.0), 0.0)
        return scores, n_pos, valid

    if metric == "hit_rate":
        scores = jnp.where(is_last & (cum_rel_k > 0), 1.0, 0.0)
        return scores, n_pos, valid

    raise ValueError(f"Metric {metric} is not scan-friendly")


def grouped_retrieval_scores(
    indexes: Array,
    preds: Array,
    target: Array,
    metric: str,
    top_k: Optional[int] = None,
    adaptive_k: bool = False,
) -> Tuple[Array, Array, Array]:
    """Per-query scores for every query in one fused device pass.

    Returns ``(scores, n_positive, valid)`` each of length N (the padded row
    count, an upper bound on the number of queries); only entries where
    ``valid`` is True are real queries. ``n_positive`` is the per-query count of
    positive targets (used by the caller for ``empty_target_action`` handling;
    for ``fall_out`` it counts negatives).

    ALIGNMENT CONTRACT: results are ROW-aligned — a query's
    score/n_positive/valid live at its LAST row in (query, -score) sort order,
    every other row holds 0 / False. Consumption must be position-agnostic
    (masked reductions over ``valid``, e.g. ``scores.sum() / valid.sum()``);
    do NOT slice a prefix (``scores[:n_queries]``).

    Every metric takes the scatter-free scan path since round 5: ndcg's
    possibly-negative float gains run through the blocked segmented cumsum
    (:func:`_segment_cumsum_float`) and r_precision's segment-total broadcast is a
    prefix+suffix scan pair, so the 174 ms-per-call ``segment_sum`` scatters at
    2^24 rows (and the one per-row gather r_precision kept) are gone entirely.
    """
    if metric not in _SCAN_METRICS:
        raise ValueError(f"Unknown grouped retrieval metric: {metric}")
    return _scan_retrieval_scores(indexes, preds, target, metric, top_k, adaptive_k)
