from metrics_tpu.ops.segment import grouped_retrieval_scores

__all__ = ["grouped_retrieval_scores"]
