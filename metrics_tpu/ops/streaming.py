"""Streaming compare-accumulate kernels for the hot classification update path.

Replaces the reference's flat eq-sum micro kernels
(``functional/classification/stat_scores.py:386-396``) with a fusion shape tuned
for the TPU XLA reduce pipeline.

Scope note (vs ``metrics_tpu/sketches/``): "streaming" here means the EXACT
compare-accumulate hot path — ``functional/classification/stat_scores.py``
routes int-label micro accuracy through :func:`eq_count` and float-logit
micro accuracy through :func:`argmax_correct_count` on every update. These
are not sketches (nothing is approximated, state is the caller's scalar
counters) and deliberately stay with the exact tier; the approximate
O(1)-state telemetry family lives in ``sketches/`` on the hashing/bucketing
kernels in ``ops/sketch.py``. Docs: the "Related streaming kernels" section
of ``docs/source/pages/sketches.rst``.

Measured design notes (TPU v5e, 819 GB/s HBM, int8 label streams, 2x1GB fresh
buffers per dispatch, one device sync per 24 dispatches):

- XLA's reduce fusion is **issue-rate bound, not HBM bound** for narrow dtypes:
  a plain ``sum(p == t)`` over two int8 streams sustains ~170 Gpreds/s
  (~340 GB/s), while pure f32/bf16 reductions cap at ~200 GB/s/stream and an
  elementwise copy (read+write) runs far slower than reductions. The ceiling for
  int8-packed reduce fusions measured ~210 Gel/s.
- Feeding MORE independent streams into ONE reduce fusion raises throughput:
  slicing each operand into quarters and summing the four int8 eq-masks
  elementwise before a single reduction ("zip4") measured +12-15% over the
  plain compare-reduce (median 138 vs 123 Gpreds/s in the same interleaved
  trial; 194 vs 171 in a faster-tunnel session). Separate fusions do NOT help
  (TPU executes fusions sequentially); the zip must stay inside one fusion.
- Pallas/Mosaic is the wrong tool for this op on v5e: int8 vector compares are
  unsupported, the xor->widen->count compute chain measured ~18 Gel/s (50x below
  VPU peak), and manual double-buffered DMA topped out at ~150 GB/s vs XLA's
  ~420 GB/s reduce-fusion reads. SWAR u32 byte-counting dies on the i8->u32
  tile relayout (materializes the whole array). Kernel-level wins here come
  from fusion shaping, not hand-written kernels.
"""
from jax import Array
import jax.numpy as jnp

# Below this, slicing overhead outweighs the extra streams.
_ZIP_MIN = 1 << 22
_ZIP_WAYS = 4


def eq_count(preds: Array, target: Array) -> Array:
    """``sum(preds == target)`` as one int32 scalar, shaped for max TPU throughput.

    Both inputs must be 1-D and equal length. For large inputs the operands are
    split into ``_ZIP_WAYS`` slices whose int8 eq-masks are summed elementwise
    inside the same fusion ("zip4"), lifting XLA's per-stream reduce issue rate.
    """
    n = preds.shape[0]
    if n < _ZIP_MIN:
        return jnp.sum(preds == target, dtype=jnp.int32)
    q = n // _ZIP_WAYS
    eq = (preds[:q] == target[:q]).astype(jnp.int8)
    for i in range(1, _ZIP_WAYS):
        eq = eq + (preds[i * q:(i + 1) * q] == target[i * q:(i + 1) * q]).astype(jnp.int8)
    count = jnp.sum(eq, dtype=jnp.int32)
    if n % _ZIP_WAYS:
        count = count + jnp.sum(preds[_ZIP_WAYS * q:] == target[_ZIP_WAYS * q:], dtype=jnp.int32)
    return count


def argmax_correct_count(probs: Array, target: Array, valid: Array = None) -> Array:
    """``sum(argmax(probs, -1) == target)`` in one dispatch — the float-logits
    micro-accuracy hot path (reference argmax-then-compare:
    functional/classification/stat_scores.py:386-396).

    Measured design notes ((2^27, 5) f32, v5e, 32-deep dispatch queue so the
    tunnel RPC latency is amortized — shallow queues measure the transport, not
    the kernel; p50 of interleaved trials, experiments/logits_exp.py has the
    full grid):

    - A pure f32 read of the same buffers (sum witness) runs 15.0 Gpreds/s
      (~320 GB/s of logical reads; the (N, 5) rows are stored padded to 8 lanes,
      so physical traffic is 1.6x that, ~58% of the 819 GB/s HBM roofline and at
      the top of the f32 read-issue rates ever observed on this chip). That is
      the read-traffic bound for any kernel consuming (N, C) f32.
    - This lowering (XLA's native variadic argmax reduce, then eq+sum) runs
      10.4 Gpreds/s = 70% of that bound.
    - A 2-lane (value, is-target-flag) ``lax.reduce`` with a keep-left combiner
      measured 12.4 (83% of bound) but is WRONG on TPU: the tree reduction does
      not preserve operand order, so exact ties resolve to an arbitrary column
      instead of the first (uniform-target aggregate tests cancel the error —
      per-row tests expose it). Every order-robust exact variant measured
      slower than native argmax: total-order (value, index) combiner 6.0
      (breaks XLA's max-select pattern match), rowmax + min-index-where-equal
      two-pass 7.7 (re-reads the tile), 3 masked max-reduces 5.0, packed-u32
      keys 10.3 (and inexact in the low 3 mantissa bits), bf16 10.2 (inexact),
      (C, N) / strided / unrolled-column layouts 2.6-5.8. Exactness is the
      product here, so the native-argmax form ships.

    Matches ``jnp.argmax`` exactly: first occurrence wins ties, NaN is maximal.
    ``probs`` is ``(M, C)`` float, ``target`` ``(M,)`` int; optional ``valid``
    bool mask ``(M,)`` removes ignored rows from the count.
    """
    idx = jnp.argmax(probs, axis=1)
    win = idx == target.astype(idx.dtype)
    if valid is not None:
        win = win & valid
    return jnp.sum(win, dtype=jnp.int32)
