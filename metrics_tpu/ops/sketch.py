"""Shared hashing/bucketing kernels for the mergeable sketch metrics.

The `metrics_tpu/sketches/` family (QuantileSketch, DistinctCount,
HistogramDrift, StreamingAUROCBound) is built on three primitives that all
live here so the sketch classes stay thin state-machines:

1. **A jit-safe 32-bit integer mixer** (:func:`fmix32`, :func:`hash_u32`):
   the murmur3 finalizer — a bijection on u32, so distinct 32-bit inputs can
   never collide at the hash layer (collisions only appear where the sketch
   itself truncates bits) — with all arithmetic in ``uint32`` (wrapping mul,
   xor-shift), nothing the TPU VPU can't vectorize and no x64 requirement.
   Floats hash by exact bit pattern after f32 canonicalization (-0.0 -> +0.0,
   mirroring the rank engine's tie semantics in ops/rank.py), so equal values
   hash equal across dtypes that widen exactly (bf16/f16 -> f32).

2. **HyperLogLog register decomposition** (:func:`hll_index_rank`): top ``p``
   hash bits select one of ``2^p`` registers, the rank is the position of the
   first set bit among the remaining ``32-p`` (via ``lax.clz`` — one VPU op,
   no loop), with the standard sentinel bit capping rank at ``33-p`` so a
   zero remainder cannot produce an unbounded shift.

3. **Log-γ bucket mapping** (:func:`log_bucket_index`) for DDSketch-style
   relative-error quantiles: bucket ``i`` covers magnitudes
   ``[min_value*γ^i, min_value*γ^(i+1))``; computed as a log difference (not
   a ratio — ``mag/min_value`` overflows f32 past ~3e29) and clamped in
   FLOAT space before the int cast so ±inf inputs land in the overflow
   sentinel instead of hitting undefined float->int conversion.

Counting goes through the tiered bincount engine (ops/histogram.py) with its
drop semantics: out-of-range indices simply vanish, so callers encode
under/overflow as sentinel indices and count them separately.
"""
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.histogram import _on_tpu, bincount_weighted


def fmix32(h: Array) -> Array:
    """Murmur3 32-bit finalizer — a full-avalanche bijection on u32.

    Every output bit depends on every input bit (the property the HLL rank
    estimator needs for its geometric-tail argument); uint32 multiplication
    wraps mod 2^32 by definition, so the whole mix is exact integer math.
    """
    h = h.astype(jnp.uint32)
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def _mix_seed(seed: int) -> int:
    """Host-side fmix32 of a golden-ratio-spread seed (static python ints).

    The seed enters :func:`hash_u32` by wrapping ADDITION of this constant,
    never by plain XOR: XOR with a constant maps an aligned consecutive input
    set onto itself (``{0..2^k-1} ^ c`` only translates the block — and for
    tiny ``c`` it IS the same set), which would make order-invariant sketch
    states bit-identical across seeds on the most common input shape there
    is, sequential ids. Addition always translates the pre-mix set.
    """
    h = (seed * 0x9E3779B9 + 1) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def hash_u32(values: Array, seed: int = 0) -> Array:
    """Canonical u32 hash of an int/float/bool array (elementwise).

    Floats are canonicalized to f32 and hashed by bit pattern with -0.0
    folded into +0.0 (IEEE equality makes them the same value; the sketch
    must agree). Integers/bools reinterpret as u32 (int32 wraps — still a
    bijection). NaN hashes to the single canonical-NaN pattern jax emits;
    callers that must drop NaNs mask before hashing.
    """
    x = jnp.asarray(values)
    if jnp.issubdtype(x.dtype, jnp.floating):
        bits = jax.lax.bitcast_convert_type(x.astype(jnp.float32), jnp.uint32)
        bits = jnp.where(bits == jnp.uint32(0x80000000), jnp.uint32(0), bits)
    else:
        bits = x.astype(jnp.uint32)
    return fmix32(bits + jnp.uint32(_mix_seed(int(seed))))


def hll_index_rank(h: Array, p: int) -> Tuple[Array, Array]:
    """(register index, rank) per hash for a ``2^p``-register HyperLogLog.

    Index = top ``p`` bits; rank = 1 + leading-zero count of the remaining
    ``32-p`` bits, capped at ``33-p`` by the sentinel bit so registers fit
    u8 with headroom for any ``4 <= p <= 16``.
    """
    if not 4 <= p <= 16:
        raise ValueError(f"HLL precision p must be in [4, 16], got {p}")
    h = h.astype(jnp.uint32)
    idx = (h >> jnp.uint32(32 - p)).astype(jnp.int32)
    w = (h << jnp.uint32(p)) | (jnp.uint32(1) << jnp.uint32(p - 1))
    rank = (jax.lax.clz(w) + 1).astype(jnp.uint8)
    return idx, rank


def hll_alpha(m: int) -> float:
    """Bias-correction constant α_m (Flajolet et al. 2007, Fig. 3)."""
    if m == 16:
        return 0.673
    if m == 32:
        return 0.697
    if m == 64:
        return 0.709
    return 0.7213 / (1.0 + 1.079 / m)


def hll_estimate(registers: Array) -> Array:
    """Cardinality estimate from u8 HLL registers, with both standard
    corrections (linear counting below 2.5m when empty registers remain;
    32-bit-hash saturation above 2^32/30). All math in f32 — the estimate's
    own standard error (1.04/sqrt(m)) dwarfs f32 rounding.
    """
    m = registers.shape[0]
    reg = registers.astype(jnp.float32)
    z = jnp.sum(jnp.exp2(-reg))
    e_raw = jnp.float32(hll_alpha(m) * m * m) / z
    v = jnp.sum((registers == 0).astype(jnp.float32))
    e_small = jnp.float32(m) * jnp.log(jnp.float32(m) / jnp.maximum(v, 1.0))
    e = jnp.where((e_raw <= 2.5 * m) & (v > 0), e_small, e_raw)
    two32 = jnp.float32(4294967296.0)
    return jnp.where(e > two32 / 30.0, -two32 * jnp.log1p(-e / two32), e)


# ----------------------------------------------------- log-γ quantile buckets


def quantile_gamma(relative_error: float) -> float:
    """γ such that one log-γ bucket's midpoint estimate has relative error
    ≤ ``relative_error`` everywhere in the bucket: γ = (1+α)/(1-α)."""
    if not 0.0 < relative_error < 1.0:
        raise ValueError(f"relative_error must be in (0, 1), got {relative_error}")
    return (1.0 + relative_error) / (1.0 - relative_error)


def log_bucket_index(mag: Array, log_gamma: float, min_value: float, num_buckets: int) -> Array:
    """Bucket index ``floor(log_γ(mag / min_value))`` clamped to ``[-1, num_buckets]``.

    ``-1`` is the underflow sentinel (0 < mag < min_value — denormals and
    sub-range values), ``num_buckets`` the overflow sentinel (too large, incl.
    +inf). Zeros also map to -1 (callers count them separately first). The
    clamp happens on the FLOAT value so inf never reaches the int cast.
    """
    safe = jnp.where(mag > 0, mag, jnp.float32(1.0))
    idx_f = jnp.floor((jnp.log(safe) - jnp.float32(math.log(min_value))) / jnp.float32(log_gamma))
    idx_f = jnp.where(mag > 0, idx_f, jnp.float32(-1.0))
    return jnp.clip(idx_f, -1.0, float(num_buckets)).astype(jnp.int32)


def bucket_midpoints(num_buckets: int, log_gamma: float, min_value: float) -> Array:
    """Per-bucket value estimate: ``min_value * γ^i * 2γ/(γ+1)`` — the point
    whose worst-case relative error over ``[min_value*γ^i, min_value*γ^(i+1))``
    is exactly α = (γ-1)/(γ+1)."""
    gamma = math.exp(log_gamma)
    i = jnp.arange(num_buckets, dtype=jnp.float32)
    return jnp.exp(
        jnp.float32(math.log(min_value)) + i * jnp.float32(log_gamma)
    ) * jnp.float32(2.0 * gamma / (gamma + 1.0))


#: above this, the <=2048-bin compare bincount tier's (bins, n) intermediate —
#: which XLA fuses into its reduction on TPU but MATERIALIZES on CPU (measured:
#: 141 GB at 2^24 rows x 2048 bins) — must not be risked off-TPU
_SCATTER_MIN_OFF_TPU = 1 << 18


def counts_into_bins(idx: Array, weights: Array, num_bins: int) -> Array:
    """Weighted histogram through the tiered bincount engine with the scatter
    fallback, drop semantics throughout (sentinel indices vanish)."""
    if idx.size >= _SCATTER_MIN_OFF_TPU and not _on_tpu(idx):
        return jnp.zeros((num_bins,), weights.dtype).at[idx].add(weights, mode="drop")
    out = bincount_weighted(idx, weights, num_bins)
    if out is None:
        out = jnp.zeros((num_bins,), weights.dtype).at[idx].add(weights, mode="drop")
    return out
