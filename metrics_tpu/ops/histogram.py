"""Static-length histogram kernels — the confusion-matrix hot path.

The reference's hot loop is ``bincount(target * C + preds, C*C)``
(functional/classification/stat_scores.py:404-410). XLA lowers ``.at[].add`` to a
serialized scatter-add on TPU, which measures ~0.1 Gelem/s on v5e — two orders of
magnitude under the memory roofline. This module provides the TPU-native tiers:

1. **Broadcast-compare** (pure XLA, portable): ``sum(where(x == bin_ids, w, 0))``
   over a ``(num_bins, N)`` virtual grid that XLA fuses without materialization.
   ~75x the scatter throughput for small bin counts; scales O(num_bins * N), so
   it dispatches only for ``num_bins <= 2048`` (measured crossover vs scatter at
   ~4096 on v5e).
2. **Pallas kernel** (TPU only): the same compare-reduce tiled explicitly —
   inputs stream HBM->VMEM in ``(8, 4096)`` blocks, each grid step accumulates a
   ``(num_bins, 1)`` partial histogram in a revisited output block. Saturates the
   measured element-compare bandwidth (~8.8 Gelem/s at 25 bins, +6% over the
   fused XLA form) and keeps VMEM bounded. Since round 6 the output block is
   additionally TILED over bins (``_BIN_TILE`` = 64 bins per grid column), so
   the kernel's ceiling is no longer the 64 bins one output block could hold.
   Round 10 closed the open 256..2048 crossover question
   (experiments/histogram_crossover.py): compare work is O(num_bins * N) in
   BOTH this tier and the fused-XLA tier, the grid confirms the compare tier
   scales linearly in bins across 256..2048 with bit-parity to the kernel
   (weighted and unweighted), and the kernel's per-element work is identical
   at every 64-bin column — the only added cost at 2048 bins is 32x grid-step
   bookkeeping on a VMEM-resident input block, «1% of a block's compare work
   at ``PALLAS_MIN_SIZE``. Verdict: the +6% anchor carries the whole range, so
   ``PALLAS_MAX_BINS`` is now 2048 (the full compare range; directional until
   a TPU round of the grid re-pins the measured ratio).

3. **One-hot MXU pair-split** (TPU only): for ``2048 < num_bins <= 2^14`` the
   bin index splits as ``hi*64 + lo`` and the histogram is the flattened
   ``onehot(hi)^T @ onehot(lo)`` — the exact kernel shape ops/confmat.py
   measured at 13x the scatter fallback (1.9-2.3 Gpreds/s at 4096 bins, C=64).
   This is the tier that makes the rank engine's 2^12-bucket key histograms
   (ops/rank.py) an O(N) MXU pass instead of a serialized scatter. Weighted
   form is exact for boolean/small-int weights only (one-hots are bf16; counts
   accumulate f32 per <=2^19 chunk) — float weights stay on the lower tiers.

All tiers drop out-of-range and negative indices exactly like the scatter path
(``mode="drop"``): a padded/ignored position simply matches no bin.
"""
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

COMPARE_MAX_BINS = 2048
PALLAS_MAX_BINS = 2048  # round 10: full compare range (experiments/histogram_crossover.py)
PAIRSPLIT_MAX_BINS = 1 << 14
PAIRSPLIT_MIN_SIZE = 1 << 18
PALLAS_MIN_SIZE = 1 << 18
_BLOCK = 1 << 15
_ROWS = 8
_BIN_TILE = 64
_PAIRSPLIT_CHUNK = 1 << 19  # per-chunk f32 count accumulation stays exact


_EAGER_COMPARE_BUDGET = 1 << 28  # max bins*N elements materialized per eager chunk


def _compare_bincount(x: Array, weights: Optional[Array], num_bins: int) -> Array:
    """Fused broadcast-compare histogram (portable, sharding-transparent).

    Comparison runs in int32 regardless of ``x.dtype`` (a sub-int32 arange would
    wrap and alias bins). Under jit XLA fuses the ``(num_bins, N)`` virtual grid;
    on concrete (eager) inputs that grid would materialize, so bins are processed
    in chunks bounded by ``_EAGER_COMPARE_BUDGET`` elements.
    """
    xm = x.astype(jnp.int32).reshape(1, -1)

    def chunk(lo: int, hi: int) -> Array:
        ids = jnp.arange(lo, hi, dtype=jnp.int32)[:, None]
        if weights is None:
            return jnp.sum((xm == ids).astype(jnp.int32), axis=1)
        return jnp.sum(jnp.where(xm == ids, weights.reshape(1, -1), jnp.zeros((), weights.dtype)), axis=1)

    if isinstance(x, jax.core.Tracer) or num_bins * x.size <= _EAGER_COMPARE_BUDGET:
        return chunk(0, num_bins)
    bins_per_chunk = max(1, _EAGER_COMPARE_BUDGET // max(x.size, 1))
    parts = [chunk(lo, min(lo + bins_per_chunk, num_bins)) for lo in range(0, num_bins, bins_per_chunk)]
    return jnp.concatenate(parts)


def _histogram_kernel(bin_tile, x_ref, w_ref, o_ref):
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)

    mapping = x_ref[0].reshape(1, _BLOCK)
    # this grid column owns bins [j*bin_tile, (j+1)*bin_tile)
    bins = jax.lax.broadcasted_iota(jnp.int32, (bin_tile, 1), 0) + pl.program_id(1) * bin_tile
    eq = mapping == bins  # (bin_tile, BLOCK)
    if w_ref is None:
        hits = eq.astype(o_ref.dtype)
    else:
        w = w_ref[0].reshape(1, _BLOCK)
        hits = jnp.where(eq, w, jnp.zeros((), w.dtype))
    o_ref[...] += jnp.sum(hits, axis=1, keepdims=True)


def _pallas_bincount(x: Array, weights: Optional[Array], num_bins: int, interpret: bool = False) -> Array:
    """Tiled compare-reduce histogram on TPU; inputs padded to a block multiple.

    The grid is (input blocks, bin tiles): each column of the grid owns a
    ``_BIN_TILE``-bin slice of the output (revisited across input blocks), so
    the bin count is VMEM-unbounded — the 64-bin ceiling of the untiled round-5
    kernel came from the single output block, not the algorithm. Compare work
    stays O(num_bins * N) regardless. The innermost grid axis is the bin tile,
    so consecutive steps revisit the SAME input block against new bins before
    streaming the next block in.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    from jax.experimental import pallas as pl

    n = x.shape[0]
    pad = (-n) % _BLOCK
    if pad:
        # padding rows carry bin id `num_bins` (matches nothing) and weight 0
        x = jnp.concatenate([x, jnp.full((pad,), num_bins, x.dtype)])
        if weights is not None:
            weights = jnp.concatenate([weights, jnp.zeros((pad,), weights.dtype)])
    x2 = x.reshape(-1, _ROWS, _BLOCK // _ROWS)
    grid = x2.shape[0]
    bin_tile = min(num_bins, _BIN_TILE)
    bins_pad = (-num_bins) % bin_tile
    n_tiles = (num_bins + bins_pad) // bin_tile
    block_spec = pl.BlockSpec((1, _ROWS, _BLOCK // _ROWS), lambda i, j: (i, 0, 0))
    out_dtype = jnp.int32 if weights is None else weights.dtype
    if weights is None:
        # weights-free kernel: no ones array, half the streamed bytes
        kernel = lambda x_ref, o_ref: _histogram_kernel(bin_tile, x_ref, None, o_ref)
        operands, in_specs = (x2,), [block_spec]
    else:
        kernel = functools.partial(_histogram_kernel, bin_tile)
        operands, in_specs = (x2, weights.reshape(-1, _ROWS, _BLOCK // _ROWS)), [block_spec, block_spec]
    out = pl.pallas_call(
        kernel,
        grid=(grid, n_tiles),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bin_tile, 1), lambda i, j: (j, 0)),
        out_shape=jax.ShapeDtypeStruct((num_bins + bins_pad, 1), out_dtype),
        interpret=interpret,
    )(*operands)
    return out[:num_bins, 0]


def _pairsplit_bincount(x: Array, weights: Optional[Array], num_bins: int) -> Array:
    """One-hot MXU histogram for large bin counts: ``hist[hi*64+lo]`` as the
    flattened ``onehot(hi)^T @ onehot(lo)`` over <=2^19-element chunks.

    The kernel shape ops/confmat.py measured at 1.9-2.3 Gpreds/s (13x scatter)
    at 4096 bins: both one-hot factors are >=64 wide so the dot runs on the
    systolic array, and per-chunk f32 accumulation of 0/1 products stays exact.
    Out-of-range/negative ids drop via a weight mask (same semantics as the
    other tiers). Weights must be boolean/small-int (bf16 one-hot carries
    them exactly only to 256); the dispatch gates float weights away.
    """
    c_hi = -(-num_bins // 64)
    in_range = (x >= 0) & (x < num_bins)
    w = in_range.astype(jnp.bfloat16) if weights is None else (
        jnp.where(in_range, weights, 0).astype(jnp.bfloat16)
    )
    xc = jnp.where(in_range, x, 0).astype(jnp.int32)
    n = xc.shape[0]
    pad = (-n) % _PAIRSPLIT_CHUNK
    if pad:
        xc = jnp.concatenate([xc, jnp.zeros((pad,), xc.dtype)])
        w = jnp.concatenate([w, jnp.zeros((pad,), w.dtype)])

    def chunk_counts(ids, wc):
        hi_oh = jax.nn.one_hot(ids >> 6, c_hi, dtype=jnp.bfloat16) * wc[:, None]
        lo_oh = jax.nn.one_hot(ids & 63, 64, dtype=jnp.bfloat16)
        return jax.lax.dot(hi_oh.T, lo_oh, preferred_element_type=jnp.float32)

    if xc.shape[0] == _PAIRSPLIT_CHUNK:
        acc = chunk_counts(xc, w)
    else:
        acc, _ = jax.lax.scan(
            lambda a, cw: (a + chunk_counts(*cw), None),
            jnp.zeros((c_hi, 64), jnp.float32),
            (xc.reshape(-1, _PAIRSPLIT_CHUNK), w.reshape(-1, _PAIRSPLIT_CHUNK)),
        )
    flat = acc.reshape(-1)[:num_bins]
    return flat.astype(jnp.int32) if weights is None else flat.astype(weights.dtype)


def _pairsplit_eligible(x: Array, weights: Optional[Array], num_bins: int) -> bool:
    int_weights = weights is None or jnp.issubdtype(weights.dtype, jnp.integer) or weights.dtype == jnp.bool_
    return (
        COMPARE_MAX_BINS < num_bins <= PAIRSPLIT_MAX_BINS
        and int_weights
        and x.size >= PAIRSPLIT_MIN_SIZE
        and _on_tpu(x)
    )


def _provably_unsharded(x: Array) -> bool:
    """True only when the aval carries sharding info AND it is fully replicated.

    When the sharding cannot be inspected we conservatively return False: feeding
    a sharded global array into ``pallas_call`` would gather/replicate it onto
    every device, defeating the sharding (the compare tier handles sharded inputs
    transparently through its reduction).
    """
    try:
        return not any(s is not None for s in x.aval.sharding.spec)
    except Exception:
        return False


def _on_tpu(x: Array) -> bool:
    """Best-effort platform of the computation, preferring real device info.

    Eager arrays expose their committed devices; under jit the tracer aval only
    carries an abstract mesh (no platform), so the ``jax_default_device`` config
    (set by ``with jax.default_device(...)``) and then the default backend decide.
    Residual limitation: an explicitly CPU-committed operand traced under plain
    ``jit`` on a TPU-default host is indistinguishable at trace time and fails
    loudly at lowering ("Only interpret mode is supported on CPU backend") —
    wrap such computations in ``jax.default_device`` to route them here.
    """
    try:
        devices = x.sharding.device_set
        return all(d.platform == "tpu" for d in devices)
    except Exception:
        pass
    default_device = jax.config.jax_default_device
    if default_device is not None:
        if isinstance(default_device, str):  # `with jax.default_device("tpu")`
            return default_device == "tpu"
        return getattr(default_device, "platform", None) == "tpu"
    return jax.default_backend() == "tpu"


def _pallas_eligible(x: Array, num_bins: int) -> bool:
    return (
        num_bins <= PALLAS_MAX_BINS
        and x.size >= PALLAS_MIN_SIZE
        and _on_tpu(x)
        and _provably_unsharded(x)
    )


def _dispatch(x: Array, weights: Optional[Array], num_bins: int) -> Optional[Array]:
    x = jnp.asarray(x).ravel()
    if weights is not None:
        weights = jnp.asarray(weights).ravel()
    if _pallas_eligible(x, num_bins):
        return _pallas_bincount(x.astype(jnp.int32), weights, num_bins)
    if num_bins <= COMPARE_MAX_BINS:
        return _compare_bincount(x, weights, num_bins)
    if _pairsplit_eligible(x, weights, num_bins):
        return _pairsplit_bincount(x.astype(jnp.int32), weights, num_bins)
    return None  # caller falls back to scatter


def bincount_weighted(x: Array, weights: Array, num_bins: int) -> Optional[Array]:
    """Weighted static-length histogram with drop semantics; fastest available tier."""
    return _dispatch(x, weights, num_bins)


def bincount(x: Array, num_bins: int) -> Optional[Array]:
    """Unweighted static-length histogram with drop semantics; fastest tier."""
    return _dispatch(x, None, num_bins)
