"""Confusion-matrix counting kernels.

The generic path is one weighted bincount of ``target*C + preds`` (reference
``functional/classification/stat_scores.py:404-410``), which XLA lowers to a
serialized scatter-add on TPU. For medium class counts the TPU-native form is a
**one-hot matmul on the MXU**: ``confmat = onehot(target)^T @ onehot(preds)`` over
chunks, with bf16 one-hots (0/1 are exact in bf16) and f32 dot accumulation cast to
int32 per chunk (chunk <= 2^19 keeps every per-chunk count f32-exact).

Measured at N=2^26 on the TPU chip: scatter 0.15 Gpreds/s at C=64; matmul
1.9-2.3 Gpreds/s (13x, bit-identical). The matmul costs 2*C^2 MAC/element, so past
C~700 it loses to the C-independent scatter: the tier is gated to
COMPARE < C^2 and C <= 512. The ``valid`` mask multiplies the target one-hot
rows, so masked elements contribute nothing (same semantics as weight-0 bincount).

Alternatives measured and rejected (round 4, same harness): int8 one-hot dot
2.08 (XLA does not hit the 2x int8 MXU rate for this shape); joint-index
histogram ``one_hot(t*C+p, C^2)`` summed by VPU reduce 0.34 or by ones-matmul
0.22 (the (chunk, C^2) one-hot is too wide); K-blocked batched dot (K=128
native systolic depth) 2.02. The extreme-K skinny outer-product dot at
~19 TFLOP/s (~10% MXU) is the bound for this op shape.
"""
from typing import Optional

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.histogram import COMPARE_MAX_BINS, _on_tpu
from metrics_tpu.utils.data import _bincount_weighted

MATMUL_MAX_CLASSES = 512
MATMUL_MIN_SIZE = 1 << 18
_CHUNK = 1 << 19


def _confmat_matmul(preds: Array, target: Array, valid: Array, num_classes: int) -> Array:
    n = preds.shape[0]
    pad = (-n) % _CHUNK
    if pad:
        preds = jnp.concatenate([preds, jnp.zeros((pad,), preds.dtype)])
        target = jnp.concatenate([target, jnp.zeros((pad,), target.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), valid.dtype)])

    def chunk_counts(pc, tc, vc):
        po = jax.nn.one_hot(pc, num_classes, dtype=jnp.bfloat16)
        to = jax.nn.one_hot(tc, num_classes, dtype=jnp.bfloat16) * vc[:, None].astype(jnp.bfloat16)
        return jax.lax.dot(to.T, po, preferred_element_type=jnp.float32).astype(jnp.int32)

    if preds.shape[0] == _CHUNK:
        return chunk_counts(preds, target, valid)

    def body(acc, ptv):
        return acc + chunk_counts(*ptv), None

    acc, _ = jax.lax.scan(
        body,
        jnp.zeros((num_classes, num_classes), jnp.int32),
        (preds.reshape(-1, _CHUNK), target.reshape(-1, _CHUNK), valid.reshape(-1, _CHUNK)),
    )
    return acc


def confusion_counts(preds: Array, target: Array, valid: Optional[Array], num_classes: int) -> Array:
    """(C, C) int32 counts indexed [target, pred]; rows with ``valid`` False drop out.

    Labels are clipped into [0, C-1] (XLA cannot raise on data; validation layers
    catch bad labels when enabled) — masked entries are clipped too but carry
    weight 0.
    """
    p = jnp.clip(preds, 0, num_classes - 1).astype(jnp.int32)
    t = jnp.clip(target, 0, num_classes - 1).astype(jnp.int32)
    if valid is None:
        valid = jnp.ones(p.shape, bool)
    if (
        num_classes**2 > COMPARE_MAX_BINS
        and num_classes <= MATMUL_MAX_CLASSES
        and p.size >= MATMUL_MIN_SIZE
        and _on_tpu(p)
    ):
        return _confmat_matmul(p, t, valid, num_classes)
    mapping = t * num_classes + p
    bins = _bincount_weighted(mapping, valid.astype(jnp.float32), minlength=num_classes**2)
    return bins.reshape(num_classes, num_classes).astype(jnp.int32)
