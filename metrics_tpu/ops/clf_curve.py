"""Device-side exact-mode classifier-curve kernels (sort + cumsum under jit).

The reference computes exact-mode (``thresholds=None``) curve metrics on host
(sklearn-style ``_binary_clf_curve``, reference
``functional/classification/precision_recall_curve.py:28-80``) because the number of
distinct thresholds is data-dependent. That is only a constraint on *curve-shaped*
outputs. Scalar reductions of the curve — AUROC, average precision — are redesigned
here to run entirely on device with static shapes:

- sort descending by score (XLA radix sort on TPU),
- cumulative tp/fp at every sample position (``cumsum``),
- tie runs collapsed by replacing every in-run value with its run-end value
  (``searchsorted`` of the sorted keys against themselves). Duplicated curve points
  are zero-width segments under trapezoidal/Riemann integration, so the result is
  exactly the unique-threshold curve value while keeping shape ``(N,)`` static.

Invalid rows (``ignore_index`` masks, fixed-capacity buffer padding) carry
``valid=False``: their sort key is forced to -inf so they form a terminal run that
adds only duplicated end points. This also makes exact mode jit/compute_from-safe —
the reference's exact mode cannot run under torch.compile/jit at all.

Since round 6 the scalar kernels (AUROC, AP, and their one-vs-rest/per-label
variants) run behind a two-tier dispatch (ops/rank.py): TPU + unsharded +
large-N routes to the bucketed rank engine's reduced-payload (u32 key, u8
label) sort — 5 B/element against this module's (f32, i32) 8 B/element, the
dominant cost of the ~125 ms bitonic network at 2^24 rows — and everything
else keeps the f32 sort below, which remains the correctness oracle (the rank
tier must match it bit-for-bit; property suite in
tests/unittests/classification/test_rank_engine.py). The curve-shaped outputs
(PR/ROC padded) stay on the oracle tier: their thresholds are user-visible f32
values and the rank tier's -0.0 canonicalization would swap -0.0 thresholds
for +0.0 (numerically equal, bitwise not).

One-vs-rest variants vmap the binary kernel over classes/labels.
"""
import sys as _sys
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops import rank as _rank
from metrics_tpu.ops.segment import segment_multi_scan
from metrics_tpu.utils.data import _next_pow2


def _run_end_counts(
    preds: Array, target: Array, valid: Array, tier: str = "sort"
) -> Tuple[Array, Array, Array, Array]:
    """(fps, tps) at every position of the descending-score sort, tie runs collapsed.

    Returns int32 ``fps``/``tps`` of shape (N,) plus the descending sort keys and
    the tie-run-end boundary mask (single source of truth for run collapsing).
    ``tps[-1]``/``fps[-1]`` are the total valid positive/negative counts.

    ``tier="rank"`` swaps the (f32 key, i32 label) sort below for the rank
    engine's bit-identical (u32 key, u8 label) construction (ops/rank.py) —
    5 B/element through the bitonic network instead of 8, and no 64 MB key
    negations. This f32 path stays the oracle the rank tier is tested against.

    TPU notes: a single multi-operand ``lax.sort`` carries the labels with the keys
    (argsort + gathers cost ~90 ms per 16M-element gather on TPU), and tie-run ends
    propagate by a reverse cummin scan of the boundary-masked cumsums —
    ``searchsorted`` is a serialized gather loop under XLA (~3.7 s at 16M vs ~35 ms
    for the scan). Since round 10 the post-sort tail is exactly TWO scan passes:
    the forward label cumsum, and ONE fused reverse multi-scan
    (ops/segment.py:segment_multi_scan) propagating both run-end streams (tps,
    run position) together — int mins are exact under reassociation, so the
    result is bit-identical to the two independent suffix-min scans it replaces.
    """
    if tier == "rank":
        return _rank.rank_run_end_counts(preds, target, valid)
    n = preds.shape[0]
    key = jnp.where(valid, preds.astype(jnp.float32), -jnp.inf)
    # ascending sort of -key == descending by key; invalid rows (-inf key) sort last
    neg_sk, st = jax.lax.sort((-key, jnp.where(valid, target.astype(jnp.int32), -1)), num_keys=1)
    sk = -neg_sk
    tps_all = jnp.cumsum((st == 1).astype(jnp.int32))
    # positions where a tie run ends; the cumsum value at the end of position i's
    # run is the value at the next boundary at-or-after i == suffix-min over the
    # boundary-masked (else +inf-like) cumsum, since cumsums are nondecreasing
    boundary = jnp.concatenate([sk[1:] != sk[:-1], jnp.ones((1,), bool)])
    big = jnp.int32(2**31 - 1)
    pos = jnp.arange(n, dtype=jnp.int32)
    tps, run_end = segment_multi_scan(
        (jnp.where(boundary, tps_all, big), jnp.where(boundary, pos, n - 1)),
        None,  # statically one global segment: suffix-min over the whole array
        ops=("min", "min"),
        reverse=True,
    )
    # valid rows sort first, so the valid count up to run_end is min(run_end+1, n_valid)
    n_valid = jnp.sum((st >= 0).astype(jnp.int32))
    fps = jnp.minimum(run_end + 1, n_valid) - tps
    return fps, tps, sk, boundary


def _roc_points(
    preds: Array, target: Array, valid: Array, tier: str = "sort"
) -> Tuple[Array, Array, Array, Array]:
    """(fpr0, tpr0) with a prepended origin, plus total positive/negative counts."""
    fps, tps, _, _ = _run_end_counts(preds, target, valid, tier)
    pos = tps[-1]
    neg = fps[-1]
    tpr = tps.astype(jnp.float32) / jnp.maximum(pos, 1)
    fpr = fps.astype(jnp.float32) / jnp.maximum(neg, 1)
    zero = jnp.zeros((1,), jnp.float32)
    return jnp.concatenate([zero, fpr]), jnp.concatenate([zero, tpr]), pos, neg


def _trapz(y: Array, x: Array) -> Array:
    return jnp.sum(jnp.diff(x) * (y[1:] + y[:-1]) * 0.5)


def mcclish_partial_auc(fpr: Array, tpr: Array, max_fpr: Array) -> Array:
    """McClish-standardized partial AUC of an ascending-``fpr`` ROC curve, pure jnp.

    Clips the curve at ``fpr == max_fpr``, interpolating ``tpr`` on the crossing
    segment (points past the clip collapse to zero-width segments, which add
    exactly 0.0 under trapezoidal integration), then applies the McClish
    correction (identity at ``max_fpr == 1``). Shared by the exact device
    kernel below and the binned path in ``functional/classification/auroc.py``
    — the binned path used host ``np.searchsorted`` before round 7, which
    concretized traced confusion state (tmlint TM-HOSTSYNC).
    """
    m = fpr.shape[0] - 1
    stop = jnp.searchsorted(fpr, max_fpr, side="right")
    lo = jnp.clip(stop - 1, 0, m)
    hi = jnp.clip(stop, 0, m)
    denom = fpr[hi] - fpr[lo]
    w = jnp.where(denom > 0, (max_fpr - fpr[lo]) / jnp.where(denom > 0, denom, 1.0), 0.0)
    interp = tpr[lo] + w * (tpr[hi] - tpr[lo])
    xc = jnp.minimum(fpr, max_fpr)
    yc = jnp.where(fpr > max_fpr, interp, tpr)
    partial_auc = _trapz(yc, xc)
    min_area = 0.5 * max_fpr**2
    return 0.5 * (1 + (partial_auc - min_area) / (max_fpr - min_area))


def _binary_auroc_kernel(
    preds: Array, target: Array, valid: Array, max_fpr: Optional[Array], tier: str = "sort"
) -> Array:
    """Exact binary AUROC; 0.0 when either class is absent (reference zeroes the
    degenerate curve via safe division — torch ``_binary_roc_compute`` — and the
    zero DOES participate in macro averages, unlike AP's NaN)."""
    fpr0, tpr0, pos, neg = _roc_points(preds, target, valid, tier)
    if max_fpr is None:
        area = _trapz(tpr0, fpr0)
    else:
        area = mcclish_partial_auc(fpr0, tpr0, max_fpr)
        # single-class data has no meaningful partial AUC (the McClish formula on a
        # zeroed curve fabricates a constant; the reference IndexErrors here) -> NaN
        return jnp.where((pos > 0) & (neg > 0), area, jnp.nan)
    # max_fpr=None: safe division zeroed the degenerate curve, so area == 0
    # exactly, matching the reference's 0.0 (not NaN)
    return area


def _binary_ap_kernel(
    preds: Array, target: Array, valid: Array, tier: str = "sort"
) -> Tuple[Array, Array]:
    """Exact binary average precision and the positive count; NaN when no positives."""
    fps, tps, _, _ = _run_end_counts(preds, target, valid, tier)
    pos = tps[-1]
    tot = (tps + fps).astype(jnp.float32)
    precision = jnp.where(tot > 0, tps.astype(jnp.float32) / jnp.where(tot > 0, tot, 1.0), 0.0)
    recall = tps.astype(jnp.float32) / jnp.maximum(pos, 1)
    ap = jnp.sum(jnp.diff(recall, prepend=0.0) * precision)
    return jnp.where(pos > 0, ap, jnp.nan), pos


# tier is a static argument: each dispatch target compiles (and caches) its own
# program, so a force_tier override can never hit a stale trace
_binary_auroc_full_j = jax.jit(partial(_binary_auroc_kernel, max_fpr=None), static_argnames=("tier",))
_binary_auroc_partial_j = jax.jit(_binary_auroc_kernel, static_argnames=("tier",))
_binary_ap_j = jax.jit(
    lambda p, t, v, tier: _binary_ap_kernel(p, t, v, tier)[0], static_argnames=("tier",)
)


def _warm_record(
    op: str,
    tier: str,
    arrays: Tuple[Array, ...],
    max_fpr: Optional[float] = None,
    bits: Optional[int] = None,
) -> None:
    """Record a rank-tier dispatch signature into the excache warm manifest.

    The kernels here are module-level jits, so the per-(shape, dtype, tier)
    compile is the replica cold-start cost prewarm eliminates. Arrays are the
    *padded* kernel inputs — pow-of-two shapes, so a prewarm replay pads to
    itself and compiles the exact executable. ``bits`` rides along for
    sketch-tier entries (the bracket kernel's static bit depth is part of its
    compile key). No-op (one dict probe) unless serve/excache.py is imported
    and recording.
    """
    _excache = _sys.modules.get("metrics_tpu.serve.excache")
    if _excache is not None and _excache.recording():
        _excache.record_rank_compile(op, tier, arrays, max_fpr, bits)


def _sketch_dispatch(
    op: str,
    obs_op: str,
    preds: Array,
    target: Array,
    valid: Array,
    tolerance: float,
    bits: int,
    kind: str,
) -> Optional[Array]:
    """Tolerance-routed sublinear tier for the scalar AUROC/AP entry points.

    Returns the certified bracket midpoint when the route is taken, None when
    the caller must fall back to the exact sort tier. The route is taken when
    (a) dispatch is forced to ``"sketch"`` (tests/prewarm replay — the width
    check is skipped), or (b) ``tolerance > 0``, the inputs are CONCRETE, and
    the bracket width at ``bits`` comes out <= tolerance. The width check needs
    the realized histogram (one O(N) compare pass, the probe cost of
    auto-dispatch; ~2-8 ms at 2^24 vs ~125 ms for the sort it replaces), so
    under a trace the certificate cannot be consulted and tolerance routing
    degrades to the exact tier — tolerance-routed METRIC classes avoid this by
    carrying histogram state directly (classification/precision_recall_curve.py).
    Served midpoints are never more than width/2 <= tolerance from the exact
    value, with the exact tier's degenerate semantics preserved (AUROC -> 0.0,
    AP -> NaN when the relevant class is absent).
    """
    forced = _rank.forced_tier()
    if forced not in (None, "sketch"):
        return None
    if forced != "sketch":
        if not tolerance or tolerance <= 0:
            return None
        if isinstance(preds, jax.core.Tracer) or isinstance(target, jax.core.Tracer):
            return None
    if kind == "auroc":
        lo, hi = _rank.sketch_auroc_bracket(preds, target, valid, bits=bits)
        pos_tot = None
    else:
        lo, hi, pos_tot = _rank.sketch_ap_bracket(preds, target, valid, bits=bits)
    if forced != "sketch" and float(hi - lo) > tolerance:
        return None
    _rank.record_dispatch("sketch", obs_op)
    _warm_record(op, "sketch", (preds, target), bits=bits)
    with _rank.rank_scope("sketch"):
        mid = 0.5 * (lo + hi)
        if pos_tot is not None:
            mid = jnp.where(pos_tot > 0, mid, jnp.nan)
        return mid


def _pad_binary(preds: Array, target: Array) -> Tuple[Array, Array, Array]:
    """Pad to the next power of two (bounded recompiles) and build the valid mask."""
    preds = jnp.asarray(preds).ravel()
    target = jnp.asarray(target).ravel().astype(jnp.int32)  # signed: -1 marks padding
    n = preds.shape[0]
    m = _next_pow2(int(n))
    if m != n:
        preds = jnp.concatenate([preds, jnp.zeros((m - n,), preds.dtype)])
        target = jnp.concatenate([target, jnp.full((m - n,), -1, target.dtype)])
    return preds, target, target >= 0


def _binary_curve_padded_kernel(preds: Array, target: Array, valid: Array) -> Tuple[Array, Array, Array, Array]:
    """Static-shape exact PR curve: (precision (N+1,), recall (N+1,), thresholds (N,), K).

    The first K entries of each array are EXACTLY the reference's unique-threshold
    curve (ascending thresholds); precision/recall pads repeat the final point
    (1, 0) — zero-width segments under integration — and threshold pads are NaN,
    so ``K = (~isnan(thresholds)).sum()`` is recoverable from the output alone.
    """
    n = preds.shape[0]
    fps, tps, sk, run_boundary = _run_end_counts(preds, target, valid)
    finite = sk != -jnp.inf  # exclude the invalid-row terminal run
    boundary = run_boundary & finite
    pos = tps[-1]
    precision_all = tps.astype(jnp.float32) / jnp.maximum(tps + fps, 1)
    # 0 positives: NaN recall (0/0), matching the eager/host path's tps/tps[-1]
    # exactly — the same metric instance must not change degenerate values
    # depending on whether compute runs eagerly or under jit
    recall_all = jnp.where(pos > 0, tps.astype(jnp.float32) / jnp.maximum(pos, 1), jnp.nan)

    # flip to ascending thresholds, then front-pack the run-end points — one
    # stable payload sort instead of argsort + 3 gathers (the ~90 ms/16M-row
    # gather trap, ops/segment.py notes)
    fb = jnp.flip(boundary)
    prec, rec, thr = _rank.stable_front_pack(
        fb, jnp.flip(precision_all), jnp.flip(recall_all), jnp.flip(sk)
    )
    k = boundary.sum()
    idx = jnp.arange(n)
    one = jnp.ones((1,), jnp.float32)
    zero = jnp.zeros((1,), jnp.float32)
    precision = jnp.concatenate([jnp.where(idx < k, prec, 1.0), one])
    recall = jnp.concatenate([jnp.where(idx < k, rec, 0.0), zero])
    thresholds = jnp.where(idx < k, thr, jnp.nan)
    return precision, recall, thresholds, k


_binary_curve_padded_j = jax.jit(_binary_curve_padded_kernel)


def binary_precision_recall_curve_padded(
    preds: Array, target: Array
) -> Tuple[Array, Array, Array, Array]:
    """Exact (``thresholds=None``) PR curve fully on device with static shapes.

    The TPU-first alternative to the reference's host-side exact mode
    (``functional/classification/precision_recall_curve.py:28-80``): runs under
    jit/shard_map/compute_from. ``target`` entries < 0 (ignore_index masks /
    buffer padding) are excluded. Returns ``(precision, recall, thresholds,
    valid_count)`` — see :func:`_binary_curve_padded_kernel` for the padding
    contract.
    """
    preds, target, valid = _pad_binary(preds, target)
    _warm_record("binary_precision_recall_curve_padded", None, (preds, target))
    return _binary_curve_padded_j(preds, target, valid)


def _binary_roc_padded_kernel(preds: Array, target: Array, valid: Array) -> Tuple[Array, Array, Array, Array]:
    """Static-shape exact ROC: (fpr (N+1,), tpr (N+1,), thresholds (N+1,), K).

    Matches the eager host layout: descending thresholds with a prepended
    (0, 0, 1.0) origin row; the first K entries are exact, pads repeat the
    terminal point and carry NaN thresholds (consumers exclude NaN-threshold
    rows, mirroring how the host path never sees pad rows). Degenerate
    single-class data zeroes the missing rate, as the host path does.
    """
    n = preds.shape[0]
    fps, tps, sk, run_boundary = _run_end_counts(preds, target, valid)
    finite = sk != -jnp.inf  # exclude the invalid-row terminal run
    boundary = run_boundary & finite
    pos = tps[-1]
    neg = fps[-1]
    tpr_all = jnp.where(pos > 0, tps.astype(jnp.float32) / jnp.maximum(pos, 1), 0.0)
    fpr_all = jnp.where(neg > 0, fps.astype(jnp.float32) / jnp.maximum(neg, 1), 0.0)
    # front-pack run-end points, keeping the descending-threshold order — one
    # stable payload sort instead of argsort + 3 gathers
    tprp, fprp, thrp = _rank.stable_front_pack(boundary, tpr_all, fpr_all, sk)
    k = boundary.sum()
    idx = jnp.arange(n)
    zero = jnp.zeros((1,), jnp.float32)
    one = jnp.ones((1,), jnp.float32)
    fpr = jnp.concatenate([zero, jnp.where(idx < k, fprp, jnp.where(neg > 0, 1.0, 0.0))])
    tpr = jnp.concatenate([zero, jnp.where(idx < k, tprp, jnp.where(pos > 0, 1.0, 0.0))])
    thresholds = jnp.concatenate([one, jnp.where(idx < k, thrp, jnp.nan)])
    return fpr, tpr, thresholds, k + 1


_binary_roc_padded_j = jax.jit(_binary_roc_padded_kernel)


def binary_roc_curve_padded(preds: Array, target: Array) -> Tuple[Array, Array, Array, Array]:
    """Exact (``thresholds=None``) ROC curve fully on device with static shapes.

    The jit-path sibling of :func:`binary_precision_recall_curve_padded`;
    ``target`` entries < 0 (ignore_index masks / buffer padding) are excluded.
    Returns ``(fpr, tpr, thresholds, valid_count)``.
    """
    preds, target, valid = _pad_binary(preds, target)
    _warm_record("binary_roc_curve_padded", None, (preds, target))
    return _binary_roc_padded_j(preds, target, valid)


def binary_auroc_exact(
    preds: Array,
    target: Array,
    max_fpr: Optional[float] = None,
    tolerance: float = 0.0,
    tolerance_bits: int = 12,
) -> Array:
    """Exact (``thresholds=None``) binary AUROC fully on device.

    ``target`` entries < 0 (ignore_index masks / buffer padding) are excluded.
    Dispatches between the f32 oracle sort, the rank engine's reduced-payload
    tier, and — when ``tolerance > 0`` certifies it — the sublinear sketch tier
    (ops/rank.py); the choice is visible under obs as ``rank.dispatch/*``.

    ``tolerance`` opts into the sketch tier: if the certified bracket width at
    ``tolerance_bits`` histogram bits is <= tolerance, the bracket midpoint is
    served from one histogram pass (no sort; true error <= width/2); otherwise
    the exact tier runs as if tolerance were 0. ``max_fpr`` not in (None, 1)
    always takes the exact tier (no partial-AUC certificate exists).
    """
    preds, target, valid = _pad_binary(preds, target)
    if max_fpr is None or max_fpr == 1:
        routed = _sketch_dispatch(
            "binary_auroc_exact", "binary_auroc", preds, target, valid, tolerance, tolerance_bits, "auroc"
        )
        if routed is not None:
            return routed
    tier = _rank.select_tier(preds)
    _rank.record_dispatch(tier, "binary_auroc")
    _warm_record("binary_auroc_exact", tier, (preds, target), max_fpr)
    with _rank.rank_scope(tier):
        # max_fpr == 1 short-circuits to the full-AUC path (reference auroc.py:92:
        # `max_fpr is None or max_fpr == 1`), which returns 0.0 — not NaN — on
        # single-class data.
        if max_fpr is None or max_fpr == 1:
            return _binary_auroc_full_j(preds, target, valid, tier=tier)
        return _binary_auroc_partial_j(preds, target, valid, jnp.float32(max_fpr), tier=tier)


def binary_average_precision_exact(
    preds: Array, target: Array, tolerance: float = 0.0, tolerance_bits: int = 12
) -> Array:
    """Exact binary average precision fully on device (tiered like AUROC,
    including the ``tolerance``-certified sublinear sketch route; no-positive
    data returns NaN on every tier)."""
    preds, target, valid = _pad_binary(preds, target)
    routed = _sketch_dispatch(
        "binary_average_precision_exact", "binary_ap", preds, target, valid, tolerance, tolerance_bits, "ap"
    )
    if routed is not None:
        return routed
    tier = _rank.select_tier(preds)
    _rank.record_dispatch(tier, "binary_ap")
    _warm_record("binary_average_precision_exact", tier, (preds, target))
    with _rank.rank_scope(tier):
        return _binary_ap_j(preds, target, valid, tier=tier)


# ------------------------------------------------------------- one-vs-rest tiers


def _binary_auroc_with_pos(
    preds: Array, target: Array, valid: Array, tier: str = "sort"
) -> Tuple[Array, Array]:
    """(AUROC, positive count) — the per-class body of the vmapped tiers.

    Absent classes score 0.0 (not NaN) and thus participate in macro averages,
    exactly like the reference's safe-division-zeroed degenerate curves.
    """
    fpr0, tpr0, pos, neg = _roc_points(preds, target, valid, tier)
    return _trapz(tpr0, fpr0), pos


def _make_ovr(kernel):
    """Multiclass tier: binarize a shared label vector one-vs-rest per class."""

    @partial(jax.jit, static_argnames=("tier",))
    def run(preds2d: Array, target: Array, tier: str = "sort") -> Tuple[Array, Array]:
        valid = target >= 0

        def per_class(p_col, c):
            return kernel(p_col, (target == c).astype(jnp.int32), valid, tier)

        return jax.vmap(per_class)(preds2d.T, jnp.arange(preds2d.shape[1]))

    return run


def _make_perlabel(kernel):
    """Multilabel tier: independent target column (and ignore mask) per label."""

    @partial(jax.jit, static_argnames=("tier",))
    def run(preds2d: Array, target2d: Array, tier: str = "sort") -> Tuple[Array, Array]:
        def per_label(p_col, t_col):
            return kernel(p_col, t_col, t_col >= 0, tier)

        return jax.vmap(per_label)(preds2d.T, target2d.T)

    return run


_ovr_auroc_j = _make_ovr(_binary_auroc_with_pos)
_ovr_ap_j = _make_ovr(_binary_ap_kernel)
_perlabel_auroc_j = _make_perlabel(_binary_auroc_with_pos)
_perlabel_ap_j = _make_perlabel(_binary_ap_kernel)


def _pad_rows(preds2d: Array, target: Array) -> Tuple[Array, Array]:
    preds2d = jnp.asarray(preds2d)
    target = jnp.asarray(target).astype(jnp.int32)  # signed: -1 marks padding
    n = preds2d.shape[0]
    m = _next_pow2(int(n))
    if m != n:
        preds2d = jnp.concatenate([preds2d, jnp.zeros((m - n, *preds2d.shape[1:]), preds2d.dtype)])
        target = jnp.concatenate([target, jnp.full((m - n, *target.shape[1:]), -1, target.dtype)])
    return preds2d, target


def _ovr_tier(preds2d: Array, op: str) -> str:
    """Tier for the vmapped variants: size gate on the per-class column length
    (each lane sorts its own column; the batched bitonic network's depth is set
    by the column, not the matrix)."""
    tier = _rank.select_tier(preds2d[:, 0] if preds2d.ndim == 2 else preds2d)
    _rank.record_dispatch(tier, op)
    return tier


def multiclass_auroc_exact(preds2d: Array, target: Array) -> Tuple[Array, Array]:
    """Per-class exact AUROC + positive-count weights; rows with target<0 excluded."""
    preds2d, target = _pad_rows(preds2d, target)
    tier = _ovr_tier(preds2d, "multiclass_auroc")
    _warm_record("multiclass_auroc_exact", tier, (preds2d, target))
    with _rank.rank_scope(tier):
        return _ovr_auroc_j(preds2d, target, tier=tier)


def multiclass_average_precision_exact(preds2d: Array, target: Array) -> Tuple[Array, Array]:
    preds2d, target = _pad_rows(preds2d, target)
    tier = _ovr_tier(preds2d, "multiclass_ap")
    _warm_record("multiclass_average_precision_exact", tier, (preds2d, target))
    with _rank.rank_scope(tier):
        return _ovr_ap_j(preds2d, target, tier=tier)


def multilabel_auroc_exact(preds2d: Array, target2d: Array) -> Tuple[Array, Array]:
    preds2d, target2d = _pad_rows(preds2d, target2d)
    tier = _ovr_tier(preds2d, "multilabel_auroc")
    _warm_record("multilabel_auroc_exact", tier, (preds2d, target2d))
    with _rank.rank_scope(tier):
        return _perlabel_auroc_j(preds2d, target2d, tier=tier)


def multilabel_average_precision_exact(preds2d: Array, target2d: Array) -> Tuple[Array, Array]:
    preds2d, target2d = _pad_rows(preds2d, target2d)
    tier = _ovr_tier(preds2d, "multilabel_ap")
    _warm_record("multilabel_average_precision_exact", tier, (preds2d, target2d))
    with _rank.rank_scope(tier):
        return _perlabel_ap_j(preds2d, target2d, tier=tier)
