"""Bucketed rank engine: exact rank statistics with the minimum-width sort.

BENCH_r05 pinned the two weakest configs on the same op: the payload-carrying
``lax.sort`` (exact AUROC 0.172 Gsamples/s with ~125 ms of the ~160 ms cycle in
the sort at 2^24 rows; retrieval 57.6 Mdocs/s, sort + scans). XLA lowers
``lax.sort`` on TPU to a bitonic network — ~log2(N)*(log2(N)+1)/2 = 300
compare-exchange passes at 2^24 — so its cost is ~(passes x operand bytes), and
the lever is BYTES PER ELEMENT, not the comparison count.

What this module does about it:

1. **Order-preserving key bijection** (:func:`monotone_key_descending`): f32
   scores map to u32 keys whose UNSIGNED ascending order is exactly descending
   score order — a total order covering ±inf and denormals, with -0.0
   canonicalized to +0.0 (IEEE equality makes them one tie run in the f32
   oracle; one shared key reproduces that) and invalid rows pinned to the -inf
   key (bit-for-bit the run structure the oracle gets by forcing -inf keys).
   Integer keys replace XLA's float total-order comparator and open the radix/
   bucket machinery below.

2. **Reduced-payload sort tier** (:func:`rank_run_end_counts`): the exact
   AUROC/AP pipeline needs only (key, label∈{neg,pos,invalid}) per row — a
   (u32, u8) sort, 5 B/element against the oracle's (f32, i32) 8 B/element —
   and every consumed quantity downstream (run-end cumulative counts, run
   positions, valid totals) is an INTEGER that depends only on the key multiset
   and per-run label counts, both invariant to within-run order. The tier
   therefore reproduces the oracle's ``(fps, tps, sk, boundary)`` bit-for-bit
   (property-tested in tests/unittests/classification/test_rank_engine.py) and
   the float tail (trapezoid / AP sums) is SHARED code on identical inputs.

3. **Bucket histograms + exact cross-bucket pair counts**
   (:func:`class_bucket_counts`, :func:`cross_bucket_pair_stats`): per-bucket
   positive/negative counts on the top key bits, whose suffix-cumsums give
   exact cross-bucket pair counts. Why this cannot replace the sort outright:
   resolving WITHIN-bucket pairs at full f32 resolution needs per-(bucket,
   sub-digit) joint counts, and the channel count doubles per resolved bit —
   past ~2^12-2^14 bins every joint-histogram formulation (compare, Pallas,
   one-hot MXU; see ops/histogram.py tiers) scales past the sort's own cost.
   Exactness below the bucket floor requires reorganizing the data, i.e. the
   sort. The histograms therefore serve (a) exact cross-bucket statistics and
   AUROC bounds for the experiment grid (experiments/rank_exp.py), (b)
   quantized-score workloads where the key domain genuinely fits the bins.

4. **Sort-slimming helpers** for the other payload-sort users:
   :func:`ranked_targets` (replaces the ``argsort(-preds)`` + gather pattern in
   functional/retrieval/* — the documented ~90 ms/16M-element gather trap in
   ops/segment.py) and :func:`stable_front_pack` (replaces the
   ``argsort(~mask, stable=True)`` + 3-gather compactions in ops/clf_curve.py).

Dispatch mirrors ops/histogram.py: TPU + provably-unsharded + large-N routes to
the rank tier; everything else keeps the f32 oracle sort, which stays the
correctness reference. ``force_tier`` pins a tier for tests/debugging; the
selection is recorded under obs counters ``rank.dispatch/<tier>`` and wrapped in
``tm.rank/<tier>`` trace scopes when observability is on (zero-overhead gate).

Cost model (v5e, 2^24 rows, from the measured notes in bench.py/segment.py —
this round's kernels are laid out against it, bench.py now attributes
sort-vs-scan time per cycle so BENCH_r06 records the real split):
oracle sort (f32+i32, 8 B/elem) ~125 ms -> (u32+u8, 5 B/elem) ~ 5/8 of that if
bandwidth-proportional; cumsum/cummax scans ~15-30 ms each (the tier also drops
the oracle's two 64 MB key negations); bucket histograms 2-8 ms per pass
(Pallas/MXU tiers).
"""
from contextlib import contextmanager
from functools import partial
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import Array

from metrics_tpu.ops.histogram import _on_tpu, _provably_unsharded, bincount_weighted

#: Below this row count the oracle path wins (dispatch + key-conversion
#: overheads dominate; the bitonic network is shallow anyway).
RANK_MIN_SIZE = 1 << 20

_EXP_MASK = jnp.uint32(0x7FFFFFFF)
_SIGN_BIT = jnp.uint32(0x80000000)
#: Descending-order key of -inf — also the pinned key for invalid rows, so they
#: merge into the same terminal run the oracle builds by forcing -inf f32 keys.
NEG_INF_KEY = jnp.uint32(0xFF800000)

_FORCED_TIER: Optional[str] = None


# --------------------------------------------------------------- key bijection


def monotone_key_descending(preds: Array, valid: Optional[Array] = None) -> Array:
    """u32 keys whose unsigned ascending order is descending score order.

    Total order on non-NaN f32: +inf -> 0x007FFFFF, ..., +0 -> 0x7FFFFFFF,
    ..., -inf -> 0xFF800000. The zero-exponent class — ±0.0 AND ±denormals —
    collapses to the +0.0 key: XLA's sort comparator flushes denormals to zero
    on both CPU and TPU (measured here: ``lax.sort`` leaves ``[1e-40, 0.0,
    1e-40, -0.0]`` interleaved and the f32 boundary check calls them one run),
    so the f32 oracle treats the whole class as a single tie run and the
    bijection must reproduce exactly that. The canonicalization runs in INTEGER
    space (exponent-field test on the raw bits) so it cannot itself be
    disturbed by flush-to-zero. Rows with ``valid`` False are pinned to
    ``NEG_INF_KEY`` (the oracle forces their keys to -inf). Inputs are NaN-free
    by the same contract the reference imposes.
    """
    x = preds.astype(jnp.float32)
    bits = jax.lax.bitcast_convert_type(x, jnp.uint32)
    # zero exponent field == zero or denormal: one tie class, keyed as +0.0
    bits = jnp.where((bits & jnp.uint32(0x7F800000)) == 0, jnp.uint32(0), bits)
    # sign set: key = bits (more-negative floats have bigger magnitudes -> bigger
    # unsigned bits); sign clear: flip the 31 value bits so bigger floats sort first
    key = jnp.where(bits >= _SIGN_BIT, bits, bits ^ _EXP_MASK)
    if valid is not None:
        key = jnp.where(valid, key, NEG_INF_KEY)
    return key


def key_to_f32_descending(keys: Array) -> Array:
    """Exact inverse of :func:`monotone_key_descending` (modulo -0 canonicalization)."""
    bits = jnp.where(keys >= _SIGN_BIT, keys, keys ^ _EXP_MASK)
    return jax.lax.bitcast_convert_type(bits.astype(jnp.uint32), jnp.float32)


# ------------------------------------------------------------------- dispatch


@contextmanager
def force_tier(tier: Optional[str]) -> Iterator[None]:
    """Pin rank-engine dispatch to ``"rank"``/``"sort"``/``"sketch"`` (None
    restores auto).

    Trace-time effect only: callers thread the selected tier into their jitted
    kernels as a static argument, so a pinned tier forms its own compile key
    and cannot leak through a stale cache entry. ``"sketch"`` applies only to
    the scalar AUROC/AP entry points (ops/clf_curve.py), which probe
    :func:`forced_tier` directly and then skip the certificate-width check;
    ops without a sketch form (curve-shaped outputs, retrieval) see
    :func:`select_tier` degrade a forced sketch to the ``"sort"`` oracle.
    """
    global _FORCED_TIER
    if tier not in (None, "rank", "sort", "sketch"):
        raise ValueError(f"unknown rank tier: {tier!r}")
    prev = _FORCED_TIER
    _FORCED_TIER = tier
    try:
        yield
    finally:
        _FORCED_TIER = prev


def forced_tier() -> Optional[str]:
    """The tier pinned by :func:`force_tier`, or None under auto dispatch."""
    return _FORCED_TIER


def select_tier(x: Array) -> str:
    """histogram.py-style tier choice: TPU + unsharded + large-N -> "rank".

    Everything else keeps the f32 oracle sort — including sharded inputs (the
    reduced-payload sort is still a global op) and small batches where the
    key-conversion passes outweigh the byte savings. Never returns
    ``"sketch"`` on its own: the sublinear tier is entered only through a
    caller-supplied error tolerance (or a forced tier) at the scalar AUROC/AP
    entry points — exactness is the default contract.
    """
    if _FORCED_TIER is not None:
        return "sort" if _FORCED_TIER == "sketch" else _FORCED_TIER
    if x.size >= RANK_MIN_SIZE and _on_tpu(x) and _provably_unsharded(x):
        return "rank"
    return "sort"


def record_dispatch(tier: str, op: str) -> None:
    """obs counters for which tier served a call; free when obs is disabled."""
    from metrics_tpu.obs import registry as _reg

    if _reg._ENABLED:
        _reg.REGISTRY.inc("rank", f"dispatch/{tier}")
        _reg.REGISTRY.inc("rank", f"op/{op}")


def rank_scope(tier: str):
    """``tm.rank/<tier>`` trace scope (built only when obs is enabled)."""
    from contextlib import nullcontext

    from metrics_tpu.obs import registry as _reg

    if not _reg._ENABLED:
        return nullcontext()
    from metrics_tpu.obs import scopes as _scopes

    return _scopes.annotate(f"tm.rank/{tier}")


# ------------------------------------------------------- reduced-payload tier


def rank_run_end_counts(preds: Array, target: Array, valid: Array) -> Tuple[Array, Array, Array, Array]:
    """Rank-tier construction of ``(fps, tps, sk, boundary)`` — bit-identical to
    the f32 oracle (ops/clf_curve.py:_run_end_counts).

    Sorts (u32 key, u8 label) — 5 B/element vs the oracle's 8 — with labels
    encoding {0: negative, 1: positive, 2: invalid}. Every consumed quantity is
    within-run-order invariant: run boundaries depend on the key multiset alone
    (identical under the bijection), and ``tps``/``fps`` read only run-END
    cumulative counts (per-run label totals are multiset properties). The f32
    ``sk`` is reconstructed through the exact inverse bijection, so downstream
    float code sees bit-identical inputs. The post-sort tail is the same two
    scan passes as the oracle: one cumsum + ONE fused reverse multi-scan for
    both run-end streams (ops/segment.py:segment_multi_scan).
    """
    from metrics_tpu.ops.segment import segment_multi_scan

    n = preds.shape[0]
    key = monotone_key_descending(preds, valid)
    lab = jnp.where(valid, (target == 1).astype(jnp.uint8), jnp.uint8(2))
    skey, slab = jax.lax.sort((key, lab), num_keys=1)
    tps_all = jnp.cumsum((slab == 1).astype(jnp.int32))
    boundary = jnp.concatenate([skey[1:] != skey[:-1], jnp.ones((1,), bool)])
    big = jnp.int32(2**31 - 1)
    pos = jnp.arange(n, dtype=jnp.int32)
    tps, run_end = segment_multi_scan(
        (jnp.where(boundary, tps_all, big), jnp.where(boundary, pos, n - 1)),
        None,  # statically one global segment: suffix-min over the whole array
        ops=("min", "min"),
        reverse=True,
    )
    n_valid = jnp.sum((slab != 2).astype(jnp.int32))
    fps = jnp.minimum(run_end + 1, n_valid) - tps
    return fps, tps, key_to_f32_descending(skey), boundary


# ------------------------------------------------- bucket histogram machinery


def bucket_counts(keys: Array, bits: int, weights: Optional[Array] = None) -> Array:
    """Histogram of the top ``bits`` key bits through the tiered bincount engine.

    2^bits bins; the fastest available tier serves (Pallas <= the tiled
    ceiling, compare <= 2048, one-hot-MXU pair-split <= 2^14 on TPU, scatter
    fallback above — ops/histogram.py). Returns int32 (or the weight dtype).
    """
    if not 1 <= bits <= 32:
        raise ValueError(f"bits must be in [1, 32], got {bits}")
    buckets = (keys >> jnp.uint32(32 - bits)).astype(jnp.int32)
    num_bins = 1 << bits
    if weights is not None:
        out = bincount_weighted(buckets, weights, num_bins)
    else:
        from metrics_tpu.ops.histogram import bincount

        out = bincount(buckets, num_bins)
    if out is None:  # past every tier: scatter fallback, drop semantics
        w = weights if weights is not None else jnp.ones(buckets.shape, jnp.int32)
        out = jnp.zeros((num_bins,), w.dtype).at[buckets].add(w, mode="drop")
    return out


def class_bucket_counts(keys: Array, pos_mask: Array, valid: Array, bits: int) -> Tuple[Array, Array]:
    """(pos_hist, neg_hist) over the top ``bits`` key bits; invalid rows drop out."""
    pos_w = (pos_mask & valid).astype(jnp.int32)
    val_w = valid.astype(jnp.int32)
    pos_hist = bucket_counts(keys, bits, pos_w)
    all_hist = bucket_counts(keys, bits, val_w)
    return pos_hist, all_hist - pos_hist


def cross_bucket_pair_stats(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """Exact (cross_gt_pairs, same_bucket_pairs) from per-bucket class counts.

    Keys are DESCENDING-order buckets (lower bucket == higher score), so a
    positive outscores every negative in a strictly higher bucket:
    ``cross_gt = sum_b pos[b] * sum_{b' > b} neg[b']``. Accumulated in f32 —
    pair counts reach N^2 and there is no int64 without x64 mode; the relative
    error (~1e-7) is documented where these feed bounds, and the EXACT metric
    path never consumes them (it runs the reduced-payload sort tier).
    """
    neg_f = neg_hist.astype(jnp.float32)
    neg_above = jnp.flip(jnp.cumsum(jnp.flip(neg_f))) - neg_f  # strictly higher buckets
    pos_f = pos_hist.astype(jnp.float32)
    return jnp.sum(pos_f * neg_above), jnp.sum(pos_f * neg_f)


def auroc_bounds_from_hists(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """[lower, upper] AUROC bounds from accumulated per-class bucket histograms.

    Same math as :func:`bucketed_auroc_bounds` but starting from the counts —
    the accumulating form the streaming sketch tier
    (``sketches/auroc_bound.py``) computes over many updates' worth of merged
    (psum'd) histograms without ever materializing the row stream.
    """
    cross, same = cross_bucket_pair_stats(pos_hist, neg_hist)
    p = jnp.sum(pos_hist).astype(jnp.float32)
    q = jnp.sum(neg_hist).astype(jnp.float32)
    denom = jnp.maximum(p * q, 1.0)
    both = (p > 0) & (q > 0)
    lo = jnp.where(both, cross / denom, 0.0)
    hi = jnp.where(both, (cross + same) / denom, 0.0)
    return lo, hi


def bucketed_auroc_bounds(
    preds: Array, target: Array, valid: Optional[Array] = None, bits: int = 12
) -> Tuple[Array, Array]:
    """[lower, upper] AUROC bounds from one histogram pass (no sort).

    The bracket width is the same-bucket opposite-class pair mass over P*N —
    the pairs the top-``bits`` histogram cannot order. Two useful exactness
    facts: the bracket collapses only when every bucket is CLASS-pure, while
    the MIDPOINT ``(lo+hi)/2`` is already the exact AUROC whenever no bucket
    mixes *distinct* scores (e.g. any <= 2^bits-value quantized domain: the
    residual same-bucket mass is then true ties, which score exactly 1/2).
    The exact dispatch path does NOT use this: it exists for the experiment
    grid (experiments/rank_exp.py), cheap progress/QA probes on streaming
    evals, and — through the histogram-input form above — the accumulating
    ``StreamingAUROCBound`` sketch metric.
    """
    if valid is None:
        valid = jnp.ones(preds.shape, bool)
    keys = monotone_key_descending(preds, valid)
    pos_hist, neg_hist = class_bucket_counts(keys, target == 1, valid, bits)
    return auroc_bounds_from_hists(pos_hist, neg_hist)


def _psi_diff(a: Array, p: Array) -> Array:
    """``ψ(a+p) − ψ(a)`` (= the harmonic sum ``Σ_{i=0..p-1} 1/(a+i)``) without
    catastrophic cancellation.

    A direct digamma difference is useless here: at stream scale ``a`` reaches
    1e7+ where ψ(a) ≈ 16 and the true difference ≈ p/a ≈ 1e-7 — below f32
    resolution of the operands. The asymptotic expansion of ψ turns every term
    into a stable small-difference form (``log1p(p/a)``, ``p/(2ab)``,
    ``p(a+b)/(12a²b²)``); its truncation error is < 1/(120 a⁴), negligible for
    a ≥ 8, and small ``a`` falls back to the exact digamma difference (where
    cancellation is harmless because the difference is O(1)).
    """
    b = a + p
    stable = jnp.log1p(p / a) + p / (2.0 * a * b) - p * (a + b) / (12.0 * a * a * b * b)
    exact = jax.scipy.special.digamma(b) - jax.scipy.special.digamma(a)
    return jnp.where(a < 8.0, exact, stable)


def average_precision_bounds_from_hists(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """[lower, upper] average-precision bounds from per-class bucket histograms.

    Buckets are in DESCENDING score order. Within a bucket the histogram has
    lost the ordering, so AP is bracketed by the two extreme arrangements:
    every positive before every negative (upper) and after (lower). Both have
    closed forms — with ``P`` positives and ``N`` negatives already emitted
    above the bucket, placing the bucket's ``p`` positives starting after
    ``k`` of its negatives contributes ``Σ_{i=1..p} (P+i)/(P+N+k+i) =
    p − (N+k)·(ψ(P+N+k+p+1) − ψ(P+N+k+1))`` — so the whole bound is two
    vectorized ψ-difference passes, O(buckets) work, no sort. The exact
    tie-collapsed AP (what ``binary_average_precision_exact`` computes) lies
    inside the bracket for every dataset: each tied run's collapsed precision
    is between its best- and worst-arrangement sums term by term.

    Pair-count caveat shared with :func:`cross_bucket_pair_stats`: counts ride
    f32 (no int64 without x64), exact to 2^24 per bucket; beyond that the
    ~1e-7 relative error is far inside the bucket-width certificate.
    """
    pos_f = pos_hist.astype(jnp.float32)
    neg_f = neg_hist.astype(jnp.float32)
    p_prev = jnp.cumsum(pos_f) - pos_f
    n_prev = jnp.cumsum(neg_f) - neg_f
    t_prev = p_prev + n_prev
    best = pos_f - n_prev * _psi_diff(t_prev + 1.0, pos_f)
    worst = pos_f - (n_prev + neg_f) * _psi_diff(t_prev + neg_f + 1.0, pos_f)
    p_total = jnp.sum(pos_f)
    denom = jnp.maximum(p_total, 1.0)
    any_pos = p_total > 0
    lo = jnp.where(any_pos, jnp.sum(worst) / denom, 0.0)
    hi = jnp.where(any_pos, jnp.sum(best) / denom, 0.0)
    return lo, hi


# ------------------------------------------------- sketch tier (tolerance route)
#
# Round 10, the sublinear serving tier: when the caller supplies an error
# ``tolerance``, the scalar AUROC/AP entry points (ops/clf_curve.py) probe one
# bucket-histogram pass — O(N) compares, no sort — and serve the certified
# bracket MIDPOINT whenever the bracket width fits the tolerance, falling back
# to the exact sort tier otherwise. The same histogram algebra backs the O(1)-
# state ``sketches.StreamingAUROCBound`` and the tolerance-routed Metric
# classes (classification/*, ``tolerance=`` ctor knob): continuous traffic then
# never materializes, sorts, or checkpoints a cat buffer unless it asked for
# exactness. The midpoint is inside the certificate by construction, so the
# served value's true error is at most width/2 <= tolerance.

#: Default histogram bit depth for tolerance-routed dispatch; matches
#: sketches.StreamingAUROCBound. 2^bits buckets over the key space — +1 bit
#: halves the expected bracket width for spread-spectrum scores.
SKETCH_DEFAULT_BITS = 12


@partial(jax.jit, static_argnames=("bits",))
def sketch_auroc_bracket(preds: Array, target: Array, valid: Array, bits: int = SKETCH_DEFAULT_BITS) -> Tuple[Array, Array]:
    """Certified [lower, upper] AUROC bracket in one histogram pass (no sort).

    Degenerate (single-class) data collapses the bracket to [0, 0] — the same
    0.0 the exact full-AUC tier returns, so the midpoint agrees with the exact
    tier's degenerate semantics.
    """
    keys = monotone_key_descending(preds, valid)
    pos_hist, neg_hist = class_bucket_counts(keys, target == 1, valid, bits)
    return auroc_bounds_from_hists(pos_hist, neg_hist)


@partial(jax.jit, static_argnames=("bits",))
def sketch_ap_bracket(
    preds: Array, target: Array, valid: Array, bits: int = SKETCH_DEFAULT_BITS
) -> Tuple[Array, Array, Array]:
    """Certified [lower, upper] average-precision bracket plus the positive
    count (callers map ``pos_total == 0`` to the exact tier's NaN)."""
    keys = monotone_key_descending(preds, valid)
    pos_hist, neg_hist = class_bucket_counts(keys, target == 1, valid, bits)
    lo, hi = average_precision_bounds_from_hists(pos_hist, neg_hist)
    return lo, hi, jnp.sum(pos_hist)


@partial(jax.jit, static_argnames=("bits",))
def hist_class_counts(
    preds: Array, pos_mask: Array, valid: Array, bits: int = SKETCH_DEFAULT_BITS
) -> Tuple[Array, Array]:
    """One lane of sketch-tier accumulation: scores -> (pos_hist, neg_hist).

    The update-side compile unit of the tolerance-routed Metric classes
    (classification/precision_recall_curve.py) — they carry histogram state
    directly, so split the bracket into this accumulating half plus the
    :func:`hist_auroc_bounds` / :func:`hist_ap_bounds` compute half. Jitted
    module-level so excache prewarm can replay the exact executable.
    """
    keys = monotone_key_descending(preds, valid)
    return class_bucket_counts(keys, pos_mask, valid, bits)


@jax.jit
def hist_auroc_bounds(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """Certified AUROC bounds from accumulated histograms; 2-D hists are
    treated as per-class lanes (vmapped — multiclass OvR / multilabel)."""
    if pos_hist.ndim == 1:
        return auroc_bounds_from_hists(pos_hist, neg_hist)
    return jax.vmap(auroc_bounds_from_hists)(pos_hist, neg_hist)


@jax.jit
def hist_ap_bounds(pos_hist: Array, neg_hist: Array) -> Tuple[Array, Array]:
    """Certified average-precision bounds from accumulated histograms; 2-D
    hists are treated as per-class lanes (vmapped)."""
    if pos_hist.ndim == 1:
        return average_precision_bounds_from_hists(pos_hist, neg_hist)
    return jax.vmap(average_precision_bounds_from_hists)(pos_hist, neg_hist)


# --------------------------------------------------------- sort-slim helpers


def ranked_targets(preds: Array, target: Array) -> Array:
    """``target`` reordered by descending ``preds`` via one payload sort.

    Replaces ``target[jnp.argsort(-preds)]`` — on TPU the argsort+gather form
    pays ~90 ms per 16M-element gather (ops/segment.py notes) where a
    payload-carrying sort does the same layout in one op. Stable, matching
    ``jnp.argsort``'s tie behavior (original order within equal scores).
    """
    _, out = jax.lax.sort((-preds, target), num_keys=1, is_stable=True)
    return out


def stable_front_pack(mask: Array, *cols: Array) -> Tuple[Array, ...]:
    """Rows where ``mask`` is True packed first, order preserved, via one sort.

    Replaces the ``order = argsort(~mask, stable=True)`` + per-column ``take``
    compaction (one sort + K gathers) with a single (u8 key, K payloads)
    stable sort.
    """
    out = jax.lax.sort(((~mask).astype(jnp.uint8),) + tuple(cols), num_keys=1, is_stable=True)
    return out[1:]
