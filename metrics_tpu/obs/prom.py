"""Prometheus text-format exposition of the obs registry, health, and series.

:func:`render` turns the current state of the instrumentation layer into the
Prometheus exposition format (text/plain; version=0.0.4) so any external
scraper can collect it without this library growing a client dependency:

- registry counters as ``tm_events_total{scope=...,name=...}``;
- registry wall timers as a ``tm_scope_seconds`` summary-style family
  (``_count``/``_sum`` per ``{scope, name}``) plus a ``tm_scope_seconds_max``
  gauge;
- health latency percentiles as a ``tm_latency_microseconds`` summary — one
  ``{op, metric, quantile}`` sample per dogfooded QuantileSketch level, with
  the per-key observation ``_count``;
- the HBM watermark and gate state as gauges;
- the sampler's latest tick (when ``obs.series`` is enabled) as
  ``tm_series_rate_per_second`` gauges plus cumulative tick/violation
  counters — the "series tails" an alerting rule wants without rescraping
  history.

Metric names follow the Prometheus conventions this module also *validates*:
``[a-zA-Z_:][a-zA-Z0-9_:]*`` names, counters ending ``_total``, label values
escaped (``\\`` ``"`` and newline). :func:`validate_exposition` is the
dependency-free structural validator (the analogue of
``obs.validate_chrome_trace`` for the scrape path); CI round-trips every
rendered page through it.

:func:`start_server` serves ``GET /metrics`` from a stdlib ``http.server``
on a daemon thread — zero new dependencies, one call to make a process
scrapeable. The same server answers ``GET /healthz`` as a readiness probe:
``200 ok`` by default, or whatever ``(status, body)`` the provider installed
via :func:`set_readiness` returns — ``serve.server.MetricsServer`` registers
its lifecycle state here, so a rolling-restart orchestrator sees ``503
starting`` until restore+prewarm complete, ``200 ready`` while admitting, and
``503 draining`` during shutdown. Nothing in this module is reachable from
the instrumented hot paths: exposition *pulls* registry/health/series state
on demand, and no server or buffer exists until :func:`start_server`.
"""
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _reg
from metrics_tpu.obs import series as _series
from metrics_tpu.utils.concurrency import thread_role

#: the Content-Type Prometheus scrapers expect from a text-format endpoint
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# one sample line: name, optional {labels}, value, optional timestamp
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)"
    r"(?:\s+(?P<ts>-?[0-9]+))?$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

_SERVER: Optional[ThreadingHTTPServer] = None
_SERVER_THREAD: Optional[threading.Thread] = None

#: the installed readiness provider for ``GET /healthz`` (None == always ok).
#: A provider is a zero-arg callable returning ``(http_status, body_text)``.
_READINESS: Optional[Any] = None


def set_readiness(provider: Any) -> None:
    """Install the ``/healthz`` provider — a zero-arg callable returning
    ``(status_code, body)``. Last caller wins (one probe per process)."""
    global _READINESS
    _READINESS = provider


def clear_readiness(provider: Any = None) -> None:
    """Remove the readiness provider. With ``provider`` given, only removes
    it if it is still the installed one (so a stopping server cannot clobber
    its replacement's registration)."""
    global _READINESS
    if provider is None or _READINESS is provider:
        _READINESS = None


def readiness_probe() -> Tuple[int, str]:
    """Evaluate the installed readiness provider; ``(200, "ok\\n")`` when none
    is installed, ``(500, ...)`` if the provider itself fails — a broken probe
    must read as not-ready, never crash the scrape thread."""
    provider = _READINESS
    if provider is None:
        return 200, "ok\n"
    try:
        status, body = provider()
        return int(status), str(body)
    except Exception as exc:  # noqa: BLE001 — a probe answers, never raises
        return 500, f"readiness provider failed: {exc}\n"


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _labels(**kv: str) -> str:
    inner = ",".join(f'{k}="{_escape_label(str(v))}"' for k, v in sorted(kv.items()))
    return "{" + inner + "}" if inner else ""


def _fmt(value: Any) -> str:
    v = float(value)
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


class _Family:
    """One metric family: HELP/TYPE header + its sample lines, in order."""

    def __init__(self, name: str, kind: str, help_text: str) -> None:
        self.name = name
        self.kind = kind
        self.help = help_text
        self.samples: List[str] = []

    def add(self, suffix: str, labels: str, value: Any) -> None:
        self.samples.append(f"{self.name}{suffix}{labels} {_fmt(value)}")

    def render(self) -> str:
        head = f"# HELP {self.name} {self.help}\n# TYPE {self.name} {self.kind}\n"
        return head + "".join(s + "\n" for s in self.samples)


def render() -> str:
    """The current obs state as one Prometheus text-format page.

    Always renderable: with everything disabled the page carries only the
    ``tm_obs_enabled 0`` gauge, so a scrape endpoint stays healthy across
    ``obs.disable()`` windows instead of 500ing.
    """
    families: List[_Family] = []

    gate = _Family("tm_obs_enabled", "gauge", "1 while the obs gate is on.")
    gate.add("", "", 1 if _reg.enabled() else 0)
    families.append(gate)

    counters = _Family(
        "tm_events", "counter",
        "Obs registry counters, labelled by scope (metric class or subsystem) and event name.",
    )
    timers = _Family(
        "tm_scope_seconds", "summary",
        "Wall time of timed obs scopes (count/sum per scope and timer name).",
    )
    timer_max = _Family(
        "tm_scope_seconds_max", "gauge", "Largest single observation per timed scope.",
    )
    for scope, names in sorted(_reg.snapshot().items()):
        for name, value in sorted(names.items()):
            if isinstance(value, dict):
                labels = _labels(scope=scope, name=name)
                timers.add("_count", labels, value.get("count", 0))
                timers.add("_sum", labels, value.get("total_s", 0.0))
                timer_max.add("", labels, value.get("max_s", 0.0))
            else:
                counters.add("_total", _labels(scope=scope, name=name), value)
    if counters.samples:
        families.append(counters)
    if timers.samples:
        families.extend([timers, timer_max])

    monitor = _health._MONITOR
    if monitor is not None:
        report = monitor.report()
        latency = _Family(
            "tm_latency_microseconds", "summary",
            "Per-(op, metric) latency quantiles from the health QuantileSketches"
            " (certified to the sketch relative_error unless the rank hit an edge bin).",
        )
        for key, row in sorted(report["latency_us"].items()):
            op, _, metric = key.partition("/")
            for field, value in sorted(row.items()):
                if field == "count":
                    latency.add("_count", _labels(op=op, metric=metric), value)
                elif field.endswith("_us"):
                    q = int(field[1:-3]) / 100.0
                    latency.add(
                        "", _labels(op=op, metric=metric, quantile=f"{q:g}"), value
                    )
        if latency.samples:
            families.append(latency)
        if report["hbm_watermark_bytes"] is not None:
            hbm = _Family(
                "tm_hbm_watermark_bytes", "gauge",
                "High-water mark of device bytes_in_use observed by the health monitor.",
            )
            hbm.add("", "", report["hbm_watermark_bytes"])
            families.append(hbm)

    # the async ingestion tier, pulled on demand: nothing here is reachable
    # from the enqueue/tick hot paths, and the import only resolves when the
    # app already imported the serve tier itself
    import sys as _sys

    _ingest = _sys.modules.get("metrics_tpu.serve.ingest")
    if _ingest is not None:
        queues = _ingest.active_queues()
        if queues:
            depth = _Family(
                "tm_ingest_queue_depth", "gauge",
                "Batches currently staged (pending, unapplied) per IngestQueue.",
            )
            capacity = _Family(
                "tm_ingest_queue_capacity", "gauge",
                "Staging-ring capacity per IngestQueue.",
            )
            ing_counters = {
                "enqueued": _Family(
                    "tm_ingest_enqueued", "counter",
                    "Batches admitted into the staging ring per IngestQueue.",
                ),
                "ticks": _Family(
                    "tm_ingest_ticks", "counter",
                    "Coalescing ticks applied per IngestQueue.",
                ),
                "coalesced_rows": _Family(
                    "tm_ingest_coalesced_rows", "counter",
                    "Input rows applied through coalescing ticks per IngestQueue.",
                ),
                "dropped": _Family(
                    "tm_ingest_dropped", "counter",
                    "Batches evicted by drop_oldest backpressure or a drain=False close.",
                ),
                "degrades": _Family(
                    "tm_ingest_degrades", "counter",
                    "Ticks that fell back to applying their batches synchronously.",
                ),
            }
            for q in queues:
                labels = _labels(queue=q.name)
                depth.add("", labels, q.depth)
                capacity.add("", labels, q.capacity)
                for stat, family in ing_counters.items():
                    family.add("_total", labels, q.stats.get(stat, 0))
            families.append(depth)
            families.append(capacity)
            families.extend(ing_counters.values())

    # the executable-cache tier, same on-demand discipline as ingest above;
    # gated on live configuration (cache routed or recording on) — a
    # merely-imported tier, or residue counters from a torn-down one, emit
    # nothing, keeping the disabled page minimal
    _excache = _sys.modules.get("metrics_tpu.serve.excache")
    if _excache is not None and (
        _excache.cache_dir() is not None or _excache.recording()
    ):
        ex_stats = _excache.stats()
        enabled_f = _Family(
            "tm_excache_persistent_enabled", "gauge",
            "1 when JAX's on-disk compilation cache is routed through"
            " serve.excache.enable_persistent_cache().",
        )
        enabled_f.add("", "", 1 if _excache.cache_dir() is not None else 0)
        families.append(enabled_f)
        ex_counters = {
            "disk_hits": _Family(
                "tm_excache_disk_hits", "counter",
                "XLA compile requests served from the persistent on-disk cache.",
            ),
            "compiles": _Family(
                "tm_excache_compiles", "counter",
                "True XLA compiles (persistent-cache misses) while the cache was enabled.",
            ),
            "prewarmed": _Family(
                "tm_excache_prewarmed", "counter",
                "Warm-manifest entries replayed into engine executable caches by prewarm().",
            ),
            "prewarm_failures": _Family(
                "tm_excache_prewarm_failures", "counter",
                "Warm-manifest entries whose replay failed and degraded to lazy compile.",
            ),
        }
        for stat, family in ex_counters.items():
            family.add("_total", "", max(0, ex_stats.get(stat, 0)))
        families.extend(ex_counters.values())
        manifest_f = _Family(
            "tm_excache_manifest_entries", "gauge",
            "Entries currently recorded in the in-process warm manifest.",
        )
        manifest_f.add("", "", len(_excache.manifest_entries()))
        families.append(manifest_f)

    # the tracing tier (tmflow), same on-demand discipline: the families only
    # render while obs.flow.enable() holds a live tracer
    _flow = _sys.modules.get("metrics_tpu.obs.flow")
    if _flow is not None and _flow.active():
        fstats = _flow.stats()
        active_f = _Family(
            "tm_flow_active", "gauge",
            "Flows currently open (minted, not yet closed) in the tmflow tracer.",
        )
        active_f.add("", "", fstats.get("open", 0))
        families.append(active_f)
        flow_counters = {
            "completed": _Family(
                "tm_flow_completed", "counter",
                "Flows closed by the tmflow tracer (includes degraded, excludes dropped).",
            ),
            "degraded": _Family(
                "tm_flow_degraded", "counter",
                "Completed flows that fell back to a degraded (synchronous) path.",
            ),
            "dropped": _Family(
                "tm_flow_dropped", "counter",
                "Traced batches evicted before launch (backpressure or queue close).",
            ),
            "sampled_out": _Family(
                "tm_flow_sampled_out", "counter",
                "Batches skipped by the 1-in-N sampling knob (no flow minted).",
            ),
        }
        for stat, family in flow_counters.items():
            family.add("_total", "", fstats.get(stat, 0))
        families.extend(flow_counters.values())
        if monitor is not None:
            flow_lat = _Family(
                "tm_flow_latency_microseconds", "summary",
                "Per-stage flow latency quantiles (queue_wait/coalesce/compile/"
                "launch/device/readback) from the tmflow health sketches.",
            )
            for key, row in sorted(monitor.report()["latency_us"].items()):
                op, _, stage = key.partition("/")
                if op != "flow_stage":
                    continue
                for field, value in sorted(row.items()):
                    if field == "count":
                        flow_lat.add("_count", _labels(stage=stage), value)
                    elif field.endswith("_us"):
                        q = int(field[1:-3]) / 100.0
                        flow_lat.add(
                            "", _labels(stage=stage, quantile=f"{q:g}"), value
                        )
            if flow_lat.samples:
                families.append(flow_lat)

    # the serving front end (tmserve), same on-demand discipline: families
    # render only while a MetricsServer is live in this process
    _srv = _sys.modules.get("metrics_tpu.serve.server")
    if _srv is not None:
        servers = _srv.active_servers()
        if servers:
            state_f = _Family(
                "tm_server_state", "gauge",
                "Lifecycle state of each MetricsServer (1 on the current state's sample).",
            )
            interval_f = _Family(
                "tm_server_tick_interval_seconds", "gauge",
                "Current (possibly adaptive) shared ticker interval per MetricsServer.",
            )
            colls_f = _Family(
                "tm_server_collections", "gauge",
                "Collections served per MetricsServer.",
            )
            srv_counters = {
                "requests": _Family(
                    "tm_server_requests", "counter",
                    "Update batches admitted through MetricsServer.enqueue().",
                ),
                "rejected": _Family(
                    "tm_server_rejected", "counter",
                    "Requests rejected for lifecycle state (not ready).",
                ),
                "rounds": _Family(
                    "tm_server_rounds", "counter",
                    "Deficit-round-robin ticker rounds that applied at least one entry.",
                ),
                "slo_breaches": _Family(
                    "tm_server_slo_breaches", "counter",
                    "Per-collection SLO budget violations observed by the control loop.",
                ),
                "drift_alerts": _Family(
                    "tm_server_drift_alerts", "counter",
                    "Drift-canary alerts (live PSI past the spec threshold).",
                ),
            }
            for s in servers:
                labels = _labels(server=s.name)
                state_f.add("", _labels(server=s.name, state=s.state), 1)
                interval_f.add("", labels, s.tick_interval_s)
                colls_f.add("", labels, len(s._collections))
                for stat, family in srv_counters.items():
                    family.add("_total", labels, s.stats.get(stat, 0))
            families.extend([state_f, interval_f, colls_f])
            families.extend(srv_counters.values())

    smp = _series._SAMPLER
    if smp is not None:
        ticks = _Family(
            "tm_series_ticks", "counter", "Sampler ticks taken since series.enable().",
        )
        ticks.add("_total", "", smp.ticks_taken)
        families.append(ticks)
        slo = _Family(
            "tm_slo_violations", "counter",
            "SLO violations observed across all sampler ticks.",
        )
        slo.add("_total", "", smp.slo_violations_total)
        families.append(slo)
        rates = _Family(
            "tm_series_rate_per_second", "gauge",
            "Per-second counter rates off the sampler's most recent tick.",
        )
        for scope, names in sorted(smp.rates().items()):
            for name, rate in sorted(names.items()):
                rates.add("", _labels(scope=scope, name=name), rate)
        if rates.samples:
            families.append(rates)

    return "".join(f.render() for f in families)


# ------------------------------------------------------------------ validator


def validate_exposition(text: str) -> int:
    """Structurally validate a text-format page; returns the sample count.

    Dependency-free mirror of the exposition-format rules this module relies
    on (what a strict scraper would reject): metric/label name charsets,
    HELP/TYPE placement (TYPE precedes its samples, at most one per family),
    known TYPE values, float-parseable sample values, counter samples ending
    in ``_total``, summary samples restricted to the base name (with an
    optional ``quantile`` label) plus ``_count``/``_sum``. Raises
    ``ValueError`` on the first violation.
    """
    if not isinstance(text, str):
        raise ValueError("exposition must be a string")
    types: Dict[str, str] = {}
    helped: set = set()
    seen_samples = 0
    sampled_families: set = set()
    for lineno, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                raise ValueError(f"line {lineno}: malformed comment {line!r}")
            kind, fam = parts[1], parts[2]
            if not _NAME_RE.match(fam):
                raise ValueError(f"line {lineno}: invalid family name {fam!r}")
            if kind == "TYPE":
                if len(parts) != 4 or parts[3] not in (
                    "counter", "gauge", "summary", "histogram", "untyped"
                ):
                    raise ValueError(f"line {lineno}: invalid TYPE line {line!r}")
                if fam in types:
                    raise ValueError(f"line {lineno}: duplicate TYPE for {fam}")
                if fam in sampled_families:
                    raise ValueError(f"line {lineno}: TYPE for {fam} after its samples")
                types[fam] = parts[3]
            else:
                if fam in helped:
                    raise ValueError(f"line {lineno}: duplicate HELP for {fam}")
                helped.add(fam)
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        name, labels, value = m.group("name"), m.group("labels"), m.group("value")
        try:
            float(value.replace("+Inf", "inf").replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(f"line {lineno}: non-numeric value {value!r}") from None
        label_names = []
        if labels:
            consumed = _LABEL_RE.sub("", labels).replace(",", "").strip()
            if consumed:
                raise ValueError(f"line {lineno}: malformed labels {{{labels}}}")
            label_names = [lm.group(1) for lm in _LABEL_RE.finditer(labels)]
            for ln in label_names:
                if not _LABEL_NAME_RE.match(ln):
                    raise ValueError(f"line {lineno}: invalid label name {ln!r}")
            if len(set(label_names)) != len(label_names):
                raise ValueError(f"line {lineno}: duplicate label names in {line!r}")
        family = _family_of(name, types)
        if family is None:
            raise ValueError(f"line {lineno}: sample {name!r} has no TYPE header")
        kind = types[family]
        suffix = name[len(family):]
        if kind == "counter" and suffix != "_total":
            raise ValueError(f"line {lineno}: counter sample {name!r} must end in _total")
        if kind == "summary" and suffix not in ("", "_count", "_sum"):
            raise ValueError(f"line {lineno}: invalid summary sample {name!r}")
        if kind == "summary" and suffix == "" and "quantile" not in label_names:
            raise ValueError(f"line {lineno}: summary sample {name!r} missing quantile label")
        if kind == "gauge" and suffix != "":
            raise ValueError(f"line {lineno}: gauge sample {name!r} must match its family")
        sampled_families.add(family)
        seen_samples += 1
    return seen_samples


def _family_of(sample_name: str, types: Dict[str, str]) -> Optional[str]:
    """Longest declared family whose name prefixes this sample name."""
    best = None
    for fam in types:
        if sample_name == fam or (
            sample_name.startswith(fam)
            and sample_name[len(fam):] in ("_total", "_count", "_sum", "_bucket")
        ):
            if best is None or len(fam) > len(best):
                best = fam
    return best


# ---------------------------------------------------------------- http server


class _MetricsHandler(BaseHTTPRequestHandler):
    # ThreadingHTTPServer invokes this on its own per-connection threads —
    # machinery tmrace cannot see statically, hence the explicit role.
    @thread_role("prom-handler")
    def do_GET(self) -> None:  # noqa: N802 — BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            status, text = readiness_probe()
            body = text.encode("utf-8")
            self.send_response(status)
            self.send_header("Content-Type", "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if path not in ("/metrics", "/"):
            self.send_response(404)
            self.end_headers()
            return
        try:
            body = render().encode("utf-8")
        except Exception as exc:  # noqa: BLE001 — a scrape must answer, not hang
            self.send_response(500)
            self.end_headers()
            self.wfile.write(str(exc).encode("utf-8"))
            return
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args: Any) -> None:  # scrapes must not spam stderr
        pass


def start_server(port: int = 9464, host: str = "127.0.0.1") -> Tuple[str, int]:
    """Serve ``GET /metrics`` on a daemon thread; returns ``(host, port)``.

    ``port=0`` binds an ephemeral port (tests); the returned port is the one
    actually bound. Idempotent: a second call replaces the first server.
    """
    global _SERVER, _SERVER_THREAD
    stop_server()
    server = ThreadingHTTPServer((host, port), _MetricsHandler)
    server.daemon_threads = True
    thread = threading.Thread(
        target=server.serve_forever, name="tmscope-prom", daemon=True
    )
    thread.start()
    _SERVER, _SERVER_THREAD = server, thread
    return server.server_address[0], server.server_address[1]


def stop_server() -> None:
    global _SERVER, _SERVER_THREAD
    server, thread = _SERVER, _SERVER_THREAD
    _SERVER = _SERVER_THREAD = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None:
        thread.join(timeout=5.0)


def server_active() -> bool:
    return _SERVER is not None
