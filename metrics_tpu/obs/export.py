"""Registry snapshot export: JSONL dumps for offline analysis / dashboards.

One registry snapshot == one JSON line, so a long-running eval can append a line
per epoch and the file stays grep/pandas-friendly. ``bench.py`` embeds the same
snapshot dict in its recorded JSON lines.

Line contract (``export_schema.json`` next to this module is the normative
JSON-schema copy; :func:`validate_snapshot` is the dependency-free validator
tests and CI run against it):

- ``schema_version``: integer stamp, bumped on breaking layout changes so
  downstream dashboards can evolve safely. Version history: 1 = the original
  ``{enabled, registry}`` pair; 2 added ``schema_version`` + ``enabled_now``
  and fixed ``enabled`` to describe the *recorded* counters; 3 added the
  optional ``flows`` stats object emitted while ``obs.flow`` is tracing
  (``validate_snapshot`` accepts every prior version — v3 only adds fields).
- ``enabled``: the gate state in effect for the counters in this line. A
  scoped ``observe()`` window that recorded counters and then exited leaves
  the instantaneous gate off while the snapshot is full of enabled-mode data —
  ``enabled`` reports True for that line (BENCH_r07 reported False there).
- ``enabled_now``: the instantaneous gate at export time.
- ``registry``: ``{scope: {name: number | {count, total_s, max_s}}}``.
"""
import json
import time
from typing import Any, Dict, Optional

from metrics_tpu.obs import registry as _reg

#: current layout stamp of exported lines (see module docstring for history)
SCHEMA_VERSION = 3


def snapshot(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Registry contents as one JSON-serializable dict (plus caller extras)."""
    import sys

    enabled_now = _reg.enabled()
    out: Dict[str, Any] = {
        "schema_version": SCHEMA_VERSION,
        "enabled": enabled_now or _reg.REGISTRY.recorded(),
        "enabled_now": enabled_now,
        "registry": _reg.snapshot(),
    }
    # tracing tier, on demand: the field only appears while a tracer is live
    _flow = sys.modules.get("metrics_tpu.obs.flow")
    if _flow is not None and _flow.active():
        out["flows"] = _flow.stats()
    if extra:
        out.update(extra)
    return out


def dump_jsonl(path: str, extra: Optional[Dict[str, Any]] = None, clock: Any = time.time) -> Dict[str, Any]:
    """Append one snapshot line to ``path``; returns the dict that was written."""
    record = snapshot(extra)
    record["time_unix"] = float(clock())
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True, default=str) + "\n")
    return record


def validate_snapshot(record: Dict[str, Any]) -> None:
    """Validate one exported line against the schema; raises ``ValueError``.

    Dependency-free mirror of ``export_schema.json`` so the check runs in CI
    without ``jsonschema`` installed.
    """
    if not isinstance(record, dict):
        raise ValueError("snapshot line must be a JSON object")
    sv = record.get("schema_version")
    if not isinstance(sv, int) or isinstance(sv, bool) or sv < 1:
        raise ValueError(f"schema_version must be a positive integer, got {sv!r}")
    for field in ("enabled", "enabled_now"):
        if not isinstance(record.get(field), bool):
            raise ValueError(f"`{field}` must be a boolean, got {record.get(field)!r}")
    reg = record.get("registry")
    if not isinstance(reg, dict):
        raise ValueError("`registry` must be an object")
    for scope, counters in reg.items():
        if not isinstance(counters, dict):
            raise ValueError(f"registry[{scope!r}] must be an object")
        for name, value in counters.items():
            if isinstance(value, bool):
                raise ValueError(f"registry[{scope!r}][{name!r}] must be numeric")
            if isinstance(value, (int, float)):
                continue
            if isinstance(value, dict):
                missing = {"count", "total_s", "max_s"} - set(value)
                if missing or not all(
                    isinstance(value[k], (int, float)) and not isinstance(value[k], bool)
                    for k in ("count", "total_s", "max_s")
                ):
                    raise ValueError(
                        f"registry[{scope!r}][{name!r}] timer must carry numeric"
                        f" count/total_s/max_s, got {value!r}"
                    )
                continue
            raise ValueError(
                f"registry[{scope!r}][{name!r}] must be a number or timer object,"
                f" got {type(value).__name__}"
            )
    if "time_unix" in record and not isinstance(record["time_unix"], (int, float)):
        raise ValueError("`time_unix` must be numeric when present")
    if "flows" in record:
        flows = record["flows"]
        if not isinstance(flows, dict):
            raise ValueError("`flows` must be an object when present")
        for name, value in flows.items():
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                raise ValueError(f"flows[{name!r}] must be numeric, got {value!r}")
