"""Registry snapshot export: JSONL dumps for offline analysis / dashboards.

One registry snapshot == one JSON line, so a long-running eval can append a line
per epoch and the file stays grep/pandas-friendly. ``bench.py`` embeds the same
snapshot dict in its recorded JSON lines.
"""
import json
import time
from typing import Any, Dict, Optional

from metrics_tpu.obs import registry as _reg


def snapshot(extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Registry contents as one JSON-serializable dict (plus caller extras)."""
    out: Dict[str, Any] = {"enabled": _reg.enabled(), "registry": _reg.snapshot()}
    if extra:
        out.update(extra)
    return out


def dump_jsonl(path: str, extra: Optional[Dict[str, Any]] = None, clock: Any = time.time) -> Dict[str, Any]:
    """Append one snapshot line to ``path``; returns the dict that was written."""
    record = snapshot(extra)
    record["time_unix"] = float(clock())
    with open(path, "a") as fh:
        fh.write(json.dumps(record, sort_keys=True, default=str) + "\n")
    return record
