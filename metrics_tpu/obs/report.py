"""Structured HBM/sharding state reports for metrics and collections.

``Metric.state_report()`` answers "what is this metric holding on device right
now": one row per registered state with dtype, shape, nbytes, the sharding spec
(where the bytes physically live on the mesh) plus a live ``layout`` row read
from the committed ``Array.sharding`` (spec / mesh axes / device count /
replicated flag — the surface ROADMAP item 1's sharded state tables report
through), and — for fixed-capacity ``CatBuffer`` states — fill vs capacity and
the overflow flag, the signal that catches unbounded cat-state growth before
it OOMs HBM.

``MetricCollection.summary()`` adds the compute-group topology: which metrics
share state (updated once per group) and the per-group HBM total, i.e. the bytes
the static grouping actually deduplicates.

Everything here is read-only and host-side; values that would require a device
sync on non-concrete (traced) arrays are reported as ``None``.
"""
from typing import Any, Dict, List, Optional

import numpy as np


def _sharding_of(x: Any) -> Optional[str]:
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return None
    spec = getattr(sharding, "spec", None)
    try:
        return str(spec) if spec is not None else str(sharding)
    except Exception:  # noqa: BLE001 — repr of exotic shardings must not break the report
        return None


def _layout_of(x: Any) -> Optional[Dict[str, Any]]:
    """Live placement of an addressable jax Array, None for host values.

    Unlike the string ``sharding`` column (kept for backward compatibility),
    this is read from the array's committed ``Array.sharding`` at report time
    — the ROADMAP item 1 success criterion wants the report to show where a
    sharded state table *actually* lives, not what the code annotated.
    """
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return None
    try:
        spec = getattr(sharding, "spec", None)
        devices = getattr(sharding, "device_set", None)
        layout: Dict[str, Any] = {
            "spec": str(spec) if spec is not None else None,
            "addressable": bool(getattr(x, "is_fully_addressable", True)),
            "num_devices": len(devices) if devices is not None else 1,
            "replicated": spec is None or all(part is None for part in spec),
        }
        mesh = getattr(sharding, "mesh", None)
        shape = getattr(mesh, "shape", None)
        if shape is not None:
            layout["mesh"] = {str(k): int(v) for k, v in dict(shape).items()}
        memory_kind = getattr(sharding, "memory_kind", None)
        if memory_kind is not None:
            layout["memory_kind"] = str(memory_kind)
        return layout
    except Exception:  # noqa: BLE001 — a half-donated or exotic array must not break the report
        return None


def _nbytes(shape, dtype) -> int:
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize


def _is_concrete_scalar(x: Any) -> bool:
    from metrics_tpu.utils.checks import _is_concrete

    return _is_concrete(x)


def _state_entry(name: str, value: Any) -> Dict[str, Any]:
    from metrics_tpu.core.state import CatBuffer

    if isinstance(value, CatBuffer):
        entry: Dict[str, Any] = {
            "name": name,
            "kind": "cat_buffer",
            "dtype": str(value.data.dtype),
            "shape": tuple(value.data.shape),
            "nbytes": _nbytes(value.data.shape, value.data.dtype),
            "sharding": _sharding_of(value.data),
            "layout": _layout_of(value.data),
            "capacity": value.capacity,
        }
        if _is_concrete_scalar(value.count):
            entry["fill"] = int(value.valid_count())
            entry["overflowed"] = bool(value.overflowed())
        else:
            entry["fill"] = None
            entry["overflowed"] = None
        return entry
    if isinstance(value, (list, tuple)):
        shapes = [tuple(np.shape(v)) for v in value]
        nbytes = sum(
            _nbytes(np.shape(v), getattr(v, "dtype", np.asarray(v).dtype)) for v in value
        )
        return {
            "name": name,
            "kind": "list",
            "dtype": str(getattr(value[0], "dtype", "?")) if value else None,
            "shape": shapes,
            "nbytes": nbytes,
            "sharding": _sharding_of(value[0]) if value else None,
            "layout": _layout_of(value[0]) if value else None,
            "length": len(value),
        }
    shape = tuple(getattr(value, "shape", np.shape(value)))
    dtype = getattr(value, "dtype", np.asarray(value).dtype)
    return {
        "name": name,
        "kind": "array",
        "dtype": str(dtype),
        "shape": shape,
        "nbytes": _nbytes(shape, dtype),
        "sharding": _sharding_of(value),
        "layout": _layout_of(value),
    }


def metric_state_report(metric: Any) -> Dict[str, Any]:
    """Structured report for one metric: per-state rows + totals."""
    states: List[Dict[str, Any]] = [
        _state_entry(name, getattr(metric, name)) for name in metric._defaults
    ]
    report = {
        "metric": type(metric).__name__,
        "update_count": metric._update_count,
        "states": states,
        "total_nbytes": int(sum(s["nbytes"] for s in states)),
    }
    fleet_size = getattr(metric, "fleet_size", None)
    if fleet_size is not None:
        # fleet-axis metric (core/fleet.py): every state row above is shaped
        # (fleet_size, *base), so per-stream HBM is total_nbytes / fleet_size
        report["fleet_size"] = int(fleet_size)
    # last checkpoint save/restore latency + step, stamped by metrics_tpu.ckpt
    ckpt_stats = getattr(metric, "_ckpt_stats", None)
    if isinstance(ckpt_stats, dict) and ckpt_stats:
        report["ckpt"] = dict(ckpt_stats)
    _attach_warmup(report)
    return report


def _attach_warmup(report: Dict[str, Any]) -> None:
    """Stamp the replica's last excache prewarm report (warmup wall time +
    per-entry outcomes) — on-demand like every serve-tier surface, so the
    report costs nothing unless the app imported serve/excache.py."""
    import sys as _sys

    _excache = _sys.modules.get("metrics_tpu.serve.excache")
    if _excache is not None:
        warmup = _excache.last_prewarm()
        if warmup is not None:
            report["warmup"] = warmup


def collection_summary(collection: Any) -> Dict[str, Any]:
    """Structured report for a MetricCollection: per-metric reports + group topology."""
    reports = {
        name: metric_state_report(m)
        for name, m in collection.items(keep_base=True, copy_state=False)
    }
    groups = []
    for members in getattr(collection, "_groups", {}).values():
        leader = members[0]
        groups.append(
            {
                "leader": leader,
                "members": list(members),
                # members alias the leader's arrays, so one group costs one leader
                "shared_nbytes": reports[leader]["total_nbytes"],
            }
        )
    naive = sum(r["total_nbytes"] for r in reports.values())
    shared = sum(g["shared_nbytes"] for g in groups) if groups else naive
    out = {
        "metrics": reports,
        "compute_groups": groups,
        "total_nbytes": shared,
        "nbytes_saved_by_groups": int(naive - shared),
    }
    ckpt_stats = getattr(collection, "_ckpt_stats", None)
    if isinstance(ckpt_stats, dict) and ckpt_stats:
        out["ckpt"] = dict(ckpt_stats)
    if getattr(collection, "fused", False):
        from metrics_tpu.core.fused import _ENGINES

        engine = _ENGINES.get(collection)
        out["fused"] = dict(engine.stats) if engine is not None else {"launches": 0}
    _attach_warmup(out)
    return out
