"""Cross-host obs aggregation: one fleet-wide snapshot from per-host telemetry.

A pod-scale serving job runs one obs registry + health monitor *per process*;
answering "what is the fleet's p99 update latency" requires merging them. This
module does that with the same algebra the metric states themselves use:

- **counters sum** — events on host A plus events on host B are fleet events;
- **timers** sum ``count``/``total_s`` and take the elementwise **max** of
  ``max_s`` (the fleet's worst single observation is the worst any host saw);
- **HBM watermarks max** — the fleet watermark is the hottest device;
- **latency QuantileSketch states merge exactly** — every sketch leaf is a
  sum-reduced int32 histogram (``sketches/base.py`` invariant), so the
  cross-host merge is elementwise integer addition, bit-identical to having
  bucketed all hosts' observations into one sketch, and the merged quantiles
  carry the same relative-error certificate.

The unit of exchange is :func:`host_snapshot` — a JSON-serializable dict
stamped with this process's ``(rank, world)`` from
:func:`metrics_tpu.parallel.collective.process_topology` (the same source the
ckpt multi-host protocol coordinates on). Transport is the caller's choice:
:func:`aggregate` merges an explicit list (tests, sidecar collectors, scrape
federation), while :func:`publish` + :func:`aggregate_dir` implement the
ckpt-style shared-directory exchange (each host atomically writes
``obs-h<rank>.json``; any host merges the directory).

Zero-overhead contract: nothing here is called from instrumented hot paths —
aggregation *pulls* registry/health state on demand, allocates only when
called, and works (degenerately) with the obs gate off.
"""
import json
import os
import tempfile
from typing import Any, Dict, List, Optional

from metrics_tpu.fault import inject as _fault
from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _reg

SCHEMA_VERSION = 1


def host_snapshot() -> Dict[str, Any]:
    """This process's obs state as one JSON-serializable, mergeable dict."""
    from metrics_tpu.parallel.collective import process_topology

    rank, world = process_topology()
    monitor = _health._MONITOR
    return {
        "schema": SCHEMA_VERSION,
        "host": rank,
        "world": world,
        "counters": _reg.snapshot(),
        "hbm_watermark_bytes": (
            monitor.hbm_watermark_bytes if monitor is not None else None
        ),
        "latency_sketches": monitor.export_sketches() if monitor is not None else {},
    }


def _merge_counters(
    into: Dict[str, Dict[str, Any]], snap: Dict[str, Dict[str, Any]]
) -> None:
    for scope, names in snap.items():
        dst = into.setdefault(scope, {})
        for name, value in names.items():
            if isinstance(value, dict):
                cur = dst.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
                cur["count"] += value.get("count", 0)
                cur["total_s"] += value.get("total_s", 0.0)
                cur["max_s"] = max(cur["max_s"], value.get("max_s", 0.0))
            else:
                dst[name] = dst.get(name, 0) + value


def _add_leaves(a: Any, b: Any) -> Any:
    """Elementwise integer addition over tolist()-shaped sketch leaves."""
    if isinstance(a, list):
        if len(a) != len(b):
            raise ValueError(
                f"sketch state leaves have mismatched lengths ({len(a)} vs {len(b)})"
            )
        return [_add_leaves(x, y) for x, y in zip(a, b)]
    return a + b


def _merge_sketches(
    into: Dict[str, Dict[str, Any]], sketches: Dict[str, Dict[str, Any]]
) -> None:
    for key, entry in sketches.items():
        cur = into.get(key)
        if cur is None:
            into[key] = {
                "params": dict(entry["params"]),
                "state": {k: json.loads(json.dumps(v)) for k, v in entry["state"].items()},
                "count": int(entry["count"]),
            }
            continue
        if cur["params"] != entry["params"]:
            raise ValueError(
                f"cannot merge latency sketch {key!r}: hosts disagree on sketch"
                f" params ({cur['params']} vs {entry['params']}) — merged quantiles"
                " would silently lose their certificate"
            )
        if set(cur["state"]) != set(entry["state"]):
            raise ValueError(
                f"cannot merge latency sketch {key!r}: state leaves differ"
                f" ({sorted(cur['state'])} vs {sorted(entry['state'])})"
            )
        cur["state"] = {
            k: _add_leaves(cur["state"][k], entry["state"][k]) for k in cur["state"]
        }
        cur["count"] += int(entry["count"])


def _quantiles_of(entry: Dict[str, Any]) -> Dict[str, Any]:
    """Compute the percentile row for one merged sketch entry."""
    import jax.numpy as jnp

    from metrics_tpu.sketches import QuantileSketch

    sk = QuantileSketch(**entry["params"])
    state = {k: jnp.asarray(v, jnp.int32) for k, v in entry["state"].items()}
    out = sk.compute_from(state)
    row: Dict[str, Any] = {"count": int(entry["count"])}
    for q, v, c in zip(
        sk.quantiles, out["quantiles"].tolist(), out["certified"].tolist()
    ):
        row[f"p{round(q * 100):d}_us"] = round(float(v), 3)
        row[f"p{round(q * 100):d}_certified"] = bool(c)
    return row


def aggregate(snapshots: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-host snapshots into one fleet-wide view with host breakdown.

    Returns ``{"hosts": <count>, "world": ..., "counters": <summed registry
    shape>, "hbm_watermark_bytes": <fleet max>, "latency_us": {key:
    percentile row computed from the merged sketch state},
    "latency_sketches": <merged, still-mergeable states>, "per_host": [...]}``
    — the merged output is itself a valid input to a higher aggregation level
    (rack → pod → fleet composes, because every reduction is associative).

    Coverage annotation: the output carries ``world_observed`` (how many
    original host snapshots this aggregate covers — inputs default to 1, an
    aggregate contributes its own count, so the field **sums**) and
    ``world_expected`` (the largest expected world any input claimed, so the
    field takes the **max**). Both reductions are associative, which is what
    lets a partial merge from :func:`aggregate_dir` keep composing up the
    rack → pod → fleet tree without losing track of who was missing.
    """
    if not snapshots:
        raise ValueError("aggregate() needs at least one host snapshot")
    counters: Dict[str, Dict[str, Any]] = {}
    sketches: Dict[str, Dict[str, Any]] = {}
    hbm: Optional[int] = None
    per_host: List[Dict[str, Any]] = []
    world = 0
    world_observed = 0
    world_expected = 0
    for snap in snapshots:
        if snap.get("schema") != SCHEMA_VERSION:
            raise ValueError(
                f"host snapshot schema {snap.get('schema')!r} != {SCHEMA_VERSION}"
            )
        _merge_counters(counters, snap.get("counters", {}))
        _merge_sketches(sketches, snap.get("latency_sketches", {}))
        host_hbm = snap.get("hbm_watermark_bytes")
        if host_hbm is not None:
            hbm = host_hbm if hbm is None else max(hbm, host_hbm)
        world = max(world, snap.get("world", 0))
        world_observed += int(snap.get("world_observed", 1))
        world_expected = max(
            world_expected, int(snap.get("world_expected", snap.get("world", 0) or 1))
        )
        per_host.append(
            {
                "host": snap.get("host"),
                "hbm_watermark_bytes": host_hbm,
                "events_total": sum(
                    value
                    for names in snap.get("counters", {}).values()
                    for value in names.values()
                    if not isinstance(value, dict)
                ),
                "latency_keys": sorted(snap.get("latency_sketches", {})),
            }
        )
    per_host.sort(key=lambda h: (h["host"] is None, h["host"]))
    return {
        "schema": SCHEMA_VERSION,
        "hosts": len(snapshots),
        "world": world,
        "world_observed": world_observed,
        "world_expected": world_expected,
        "counters": counters,
        "hbm_watermark_bytes": hbm,
        "latency_us": {key: _quantiles_of(entry) for key, entry in sketches.items()},
        "latency_sketches": sketches,
        "per_host": per_host,
    }


# ------------------------------------------------- shared-directory exchange


def _host_path(dirpath: str, rank: int) -> str:
    return os.path.join(dirpath, f"obs-h{rank:04d}.json")


def publish(dirpath: str, snapshot: Optional[Dict[str, Any]] = None) -> str:
    """Write this host's snapshot to ``dirpath/obs-h<rank>.json``, atomically.

    The ckpt-style exchange for launchers without a shared network plane:
    every process publishes into one shared directory (tmp + fsync + rename,
    so readers never see a torn file), then any process calls
    :func:`aggregate_dir`. Returns the path written.
    """
    snap = host_snapshot() if snapshot is None else snapshot
    path = _host_path(dirpath, int(snap["host"]))
    if _fault._SCHEDULE is not None:
        _fault.fire("agg.publish", host=snap.get("host"))
    os.makedirs(dirpath, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=dirpath, prefix=".obs-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(snap, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def aggregate_dir(
    dirpath: str,
    expect_world: Optional[int] = None,
    *,
    timeout_s: Optional[float] = None,
    min_world: Optional[int] = None,
    poll_interval_s: float = 0.05,
) -> Dict[str, Any]:
    """Merge every ``obs-h*.json`` under ``dirpath`` (see :func:`aggregate`).

    Two modes:

    - **Strict** (default, neither ``timeout_s`` nor ``min_world`` given):
      ``expect_world`` makes a partial exchange loud — fewer published hosts
      than the expected world raises instead of silently reporting a partial
      fleet, and an unreadable/torn snapshot file propagates its error.
    - **Tolerant** (``timeout_s`` and/or ``min_world`` given): wait up to
      ``timeout_s`` seconds (polling every ``poll_interval_s``) for
      ``expect_world`` hosts to publish, then merge whatever arrived —
      skipping unreadable files — and return a **coverage-annotated partial
      aggregate**: ``world_observed`` says how many hosts actually landed,
      ``world_expected`` what the fleet should have been (both still compose
      associatively through further :func:`aggregate` levels). ``min_world``
      is the floor under the partial answer: fewer readable snapshots than
      that raises, because an "aggregate" covering almost nobody is worse
      than an error.
    """
    tolerant = timeout_s is not None or min_world is not None
    target = expect_world if expect_world is not None else min_world

    def read_all() -> tuple:
        snapshots: List[Dict[str, Any]] = []
        skipped = 0
        for entry in sorted(os.listdir(dirpath)):
            if not (entry.startswith("obs-h") and entry.endswith(".json")):
                continue
            try:
                if _fault._SCHEDULE is not None:
                    _fault.fire("agg.read", file=entry)
                with open(os.path.join(dirpath, entry)) as f:
                    snapshots.append(json.load(f))
            except (OSError, ValueError):
                if not tolerant:
                    raise
                skipped += 1
        return snapshots, skipped

    snapshots, skipped = read_all()
    if timeout_s is not None and target is not None and len(snapshots) < target:
        from metrics_tpu.parallel.collective import wait_for_world

        latest = {"snaps": snapshots, "skipped": skipped}

        def observed() -> int:
            latest["snaps"], latest["skipped"] = read_all()
            return len(latest["snaps"])

        wait_for_world(
            observed, target, timeout_s=timeout_s, poll_interval_s=poll_interval_s
        )
        snapshots, skipped = latest["snaps"], latest["skipped"]
    if min_world is not None and len(snapshots) < min_world:
        raise ValueError(
            f"aggregate_dir: only {len(snapshots)} readable host snapshots under"
            f" {dirpath!r} after waiting, below min_world={min_world}"
            f" ({skipped} unreadable)"
        )
    if not tolerant and expect_world is not None and len(snapshots) < expect_world:
        raise ValueError(
            f"aggregate_dir: found {len(snapshots)} host snapshots under"
            f" {dirpath!r}, expected {expect_world}"
        )
    out = aggregate(snapshots)
    if expect_world is not None:
        out["world_expected"] = max(out["world_expected"], int(expect_world))
    return out


def fleet_snapshot() -> Dict[str, Any]:
    """This process's view of the fleet — in a single-process runtime, the
    aggregate of its own snapshot (the world==1 degenerate case)."""
    return aggregate([host_snapshot()])
