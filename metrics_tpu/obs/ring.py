"""A fixed-capacity ring buffer shared by the flight recorder and ingest tier.

Two very different producers converged on the same discipline: the flight
recorder (``obs/flight.py``) appends post-mortem events from instrumented hot
paths, and the async ingestion tier (``serve/ingest.py``) stages pending
update batches for the coalescing tick thread. Both need the same three
properties, factored here so there is exactly one implementation with two
regression-tested users:

- **Fixed capacity, allocate-once**: the backing ``collections.deque`` is
  sized at construction; a full ring either evicts the oldest item
  (:meth:`append` — the flight recorder's "last K events" semantics) or
  refuses the new one (:meth:`try_append` — the ingest tier's backpressure
  semantics decide what happens next).
- **GIL-atomic lock-free append**: ``deque.append`` with ``maxlen`` is atomic
  under the GIL, so the hot-path producer never takes a lock.
- **Drain-under-lock**: consumers that must not lose or double-see items
  (:meth:`drain`, :meth:`pop_oldest`, :meth:`try_append`) serialize on one
  internal lock; the lock-free :meth:`snapshot` instead retries the rare
  ``RuntimeError`` from iterating concurrently with an append.
"""
import threading
from collections import deque
from typing import Any, List, Optional

__all__ = ["Ring"]


class Ring:
    """Bounded FIFO ring: lock-free evicting append, locked exact drain."""

    __slots__ = ("_dq", "_capacity", "_lock")

    def __init__(self, capacity: int) -> None:
        if capacity < 1:
            raise ValueError(f"ring capacity must be >= 1, got {capacity}")
        self._capacity = int(capacity)
        self._dq: deque = deque(maxlen=self._capacity)
        self._lock = threading.Lock()

    @property
    def capacity(self) -> int:
        return self._capacity

    def __len__(self) -> int:
        return len(self._dq)

    @property
    def full(self) -> bool:
        return len(self._dq) >= self._capacity

    # ------------------------------------------------------------ producing

    def append(self, item: Any) -> None:
        """Lock-free append; silently evicts the oldest item when full.

        ``deque.append`` with ``maxlen`` is atomic under the GIL — this is the
        flight-recorder hot path and must never block.
        """
        self._dq.append(item)

    def try_append(self, item: Any) -> bool:
        """Locked append that refuses (returns False) instead of evicting.

        The check-then-append runs under the ring lock so concurrent
        producers can never overshoot capacity or silently drop an item —
        the contract the ingest backpressure policies are built on.
        """
        with self._lock:
            if len(self._dq) >= self._capacity:
                return False
            self._dq.append(item)
            return True

    # ------------------------------------------------------------ consuming

    def pop_oldest(self) -> Optional[Any]:
        """Remove and return the oldest item, or None when empty (locked)."""
        with self._lock:
            try:
                return self._dq.popleft()
            except IndexError:
                return None

    def drain(self, limit: Optional[int] = None) -> List[Any]:
        """Remove and return up to ``limit`` oldest items (all, when None).

        Runs under the ring lock: every item lands in exactly one drain call
        even with concurrent producers and multiple consumers.
        """
        out: List[Any] = []
        with self._lock:
            n = len(self._dq) if limit is None else min(limit, len(self._dq))
            for _ in range(n):
                out.append(self._dq.popleft())
        return out

    def snapshot(self) -> List[Any]:
        """Non-destructive copy, oldest first, without locking the producer.

        Iterating a deque while another thread appends can raise
        ``RuntimeError`` — retry rather than making :meth:`append` pay for a
        lock it never needs.
        """
        for _ in range(8):
            try:
                return list(self._dq)
            except RuntimeError:
                continue
        return list(self._dq)

    def clear(self) -> None:
        with self._lock:
            self._dq.clear()
