"""Retrace / compile-storm detection.

The classic silent TPU perf bug: a metric fed slightly different abstract shapes
(ragged last batch, a dtype flip, a Python-scalar-vs-array argument) retraces and
recompiles on every step. XLA gives no warning; the job just runs 100x slower.

Detection is host-side and cheap: every instrumented ``update`` fingerprints the
**abstract** structure of its inputs (pytree paths + shapes + dtypes — never
values, never device syncs). A metric instance that accumulates more than one
distinct fingerprint is retracing its jitted update; past
``RETRACE_WARN_THRESHOLD`` distinct fingerprints it is in a compile storm and a
rate-limited warning (once per instance) names the offending metric and the
fingerprints seen. ``jax.monitoring`` compile events, when available, are
counted alongside (``registry._register_compile_listener``) as corroboration.
"""
import threading
import warnings
from typing import Any, Tuple

import numpy as np

from metrics_tpu.obs import flight as _flight
from metrics_tpu.obs import registry as _reg

#: Distinct input fingerprints at which a metric is declared "storming".
RETRACE_WARN_THRESHOLD = 2

#: Distinct fingerprints seen per metric CLASS across all instances. The
#: per-instance dedup below means a fleet of N instances each seeing the same
#: two signatures records N `retraces` but tells you nothing about signature
#: churn at the class level; the `retrace_signatures` counter (one increment
#: per signature beyond the first, class-wide) is what the JSONL export
#: attributes to a class — matching the class-level rule IDs tmlint emits
#: (metrics_tpu/analysis/, TM-RETRACE).
_CLASS_FINGERPRINTS: dict = {}

#: Classes already warned about class-level signature churn (once per class).
_CLASS_RETRACE_WARNED: set = set()

#: Guards the class-level maps above: the async ckpt writer thread can drive
#: instrumented updates concurrently with the training thread, and dict
#: setdefault + set mutation is not atomic as a sequence.
_CLASS_LOCK = threading.Lock()


def _fingerprint_leaf(x: Any) -> Tuple:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if isinstance(x, (list, tuple)):
        return (type(x).__name__,) + tuple(_fingerprint_leaf(v) for v in x)
    if isinstance(x, dict):
        return ("dict",) + tuple((k, _fingerprint_leaf(x[k])) for k in sorted(map(str, x)))
    if isinstance(x, (bool, int, float, str, bytes, type(None))):
        # static values participate in the jit cache key, so a varying Python
        # scalar is itself a retrace source — fingerprint the value
        return ("py", type(x).__name__, x)
    return ("obj", type(x).__name__)


def fingerprint(args: Tuple, kwargs: dict) -> Tuple:
    """Abstract (shape/dtype/structure) fingerprint of an update's inputs."""
    return (
        tuple(_fingerprint_leaf(a) for a in args),
        tuple((k, _fingerprint_leaf(kwargs[k])) for k in sorted(kwargs)),
    )


def check_update(metric: Any, args: Tuple, kwargs: dict) -> None:
    """Record one update's input fingerprint on ``metric``; warn on a storm.

    Called from ``Metric._wrap_update`` only when obs is enabled. State lives on
    the instance (``_obs_fingerprints`` / ``_obs_retrace_warned``) so detector
    lifetime matches metric lifetime with no global id() maps.
    """
    fp = fingerprint(args, kwargs)
    seen = metric.__dict__.get("_obs_fingerprints")
    if seen is None:
        seen = set()
        object.__setattr__(metric, "_obs_fingerprints", seen)
    if fp in seen:
        return
    first = not seen
    seen.add(fp)
    name = type(metric).__name__
    # class-level aggregation rides every instance-level miss (set-union cost
    # only on new-signature events, never on the steady-state early return);
    # the map mutation happens under a lock, the warning outside it
    with _CLASS_LOCK:
        class_seen = _CLASS_FINGERPRINTS.setdefault(name, set())
        class_first = not class_seen
        new_signature = fp not in class_seen
        if new_signature:
            class_seen.add(fp)
        n_class = len(class_seen)
        warn_class = (
            new_signature
            and n_class > RETRACE_WARN_THRESHOLD
            and name not in _CLASS_RETRACE_WARNED
            and getattr(metric, "fleet_size", None) is None
        )
        if warn_class:
            _CLASS_RETRACE_WARNED.add(name)
    if new_signature and not class_first:
        _reg.REGISTRY.inc(name, "retrace_signatures")
    if warn_class:
        # class-level churn with per-instance dedup intact means MANY
        # instances of the same class each compile their own update — the
        # eager-fleet anti-pattern. A single fleet instance shares one
        # compiled executable across every stream.
        warnings.warn(
            f"metrics_tpu.obs: `{name}` has seen {n_class} distinct"
            " update signatures across its instances (class-wide). If these"
            " are per-stream/per-tenant copies of the same metric, replace"
            f" them with one fleet instance — `{name}(..., fleet_size=N)`"
            " updated via `update(..., stream_ids=...)` — which compiles one"
            " executable and runs one launch for all streams.",
            RuntimeWarning,
            stacklevel=3,
        )
    if not first:
        _reg.REGISTRY.inc(name, "retraces")
        if _flight._RING is not None:
            _flight.record("retrace", metric=name, signatures=len(seen))
    if len(seen) > RETRACE_WARN_THRESHOLD and not metric.__dict__.get("_obs_retrace_warned", False):
        object.__setattr__(metric, "_obs_retrace_warned", True)
        _reg.REGISTRY.inc(name, "retrace_warnings")
        shapes = _summarize(seen)
        warnings.warn(
            f"metrics_tpu.obs: compile storm suspected — `{name}.update` has now seen"
            f" {len(seen)} distinct input shape/dtype signatures ({shapes}). Every new"
            " signature retraces and recompiles the jitted update. Pad inputs to a"
            " fixed shape (or bucket them) to stop the recompilation.",
            RuntimeWarning,
            stacklevel=3,
        )


def _summarize(seen: set, limit: int = 4) -> str:
    def leaf_shapes(fp: Tuple) -> str:
        arrs = [t for t in fp[0] if isinstance(t, tuple) and t and t[0] == "arr"]
        return "/".join("x".join(map(str, t[1])) + f":{t[2]}" for t in arrs) or "<no-arrays>"

    items = sorted(leaf_shapes(fp) for fp in seen)
    head = ", ".join(items[:limit])
    return head + (f", ... +{len(items) - limit} more" if len(items) > limit else "")


def reset_detector(metric: Any) -> None:
    """Forget a metric's fingerprint history (used by tests)."""
    metric.__dict__.pop("_obs_fingerprints", None)
    metric.__dict__.pop("_obs_retrace_warned", None)


def reset_class_detector(name: Any = None) -> None:
    """Forget class-level fingerprint history — all classes, or one class /
    metric class object (used by tests and long-lived eval loops that rotate
    workloads)."""
    with _CLASS_LOCK:
        if name is None:
            _CLASS_FINGERPRINTS.clear()
            _CLASS_RETRACE_WARNED.clear()
            return
        if isinstance(name, type):
            name = name.__name__
        _CLASS_FINGERPRINTS.pop(name, None)
        _CLASS_RETRACE_WARNED.discard(name)


def nbytes_of(x: Any) -> int:
    """Static (trace-safe) byte size of an array-like; 0 when unknowable."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except Exception:  # noqa: BLE001 — exotic dtypes must not break accounting
        return 0
