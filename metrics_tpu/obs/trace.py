"""Perfetto / Chrome ``trace_event`` export of the ``tm.*`` runtime timeline.

Turns the flight-recorder window (``obs/flight.py``) into a JSON object-format
trace — ``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": ...}``
— loadable in ``chrome://tracing`` and https://ui.perfetto.dev, and
correlatable with a ``jax.profiler`` XProf capture of the same run: the host
slices here carry the same ``tm.update/<Metric>`` / ``tm.fused/step`` names as
the ``jax.named_scope`` annotations baked into the HLO.

Track model — one track per metric/engine:

- every ``scope`` flight event (a timed ``tm.*`` window from
  ``obs/scopes.py``) becomes a complete slice (``"ph": "X"``) on the track of
  the metric or engine that owns it (``tm.update/BinaryAccuracy`` → track
  ``BinaryAccuracy``, ``tm.fused/step`` → track ``fused``);
- point events (``dispatch``, ``retrace``, ``fused_cache_miss``,
  ``fleet_route``, ``merge``, ``ckpt_*``) become instants (``"ph": "i"``) on
  the owning track, with their structured fields — input avals, cache keys,
  routed rows, commit steps — in ``args`` where the Perfetto UI shows them on
  click;
- tracks are named via ``thread_name`` metadata events, so the timeline reads
  as one row per metric/engine rather than anonymous tids. Events that carry a
  ``queue``/``engine`` instance field get the instance suffixed onto the track
  (``ingest_tick/replica-a``) so two queues sharing a metric class never
  collide on one row;
- ``flow_complete`` events (tmflow, ``obs/flow.py``) are rendered as **flow
  arrows**: per flow an enqueue slice on ``ingest/<queue>``, ONE launch slice
  per coalesced tick on ``launcher/<queue>``, a device slice on
  ``compute/<queue>``, and ``ph s/t/f`` flow events (keyed by the flow's
  integer id) linking them — the Perfetto UI draws the fan-in arrows from
  every staged batch into its single launch.

Naming note: the *module* ``metrics_tpu.obs.trace`` (this file) is the
exporter; the *attribute* ``metrics_tpu.obs.trace`` remains the XProf capture
context manager from ``obs/scopes.py`` for backward compatibility — use
``obs.export_chrome_trace(...)`` / ``obs.chrome_trace_events()`` (re-exported
at the package root) rather than ``obs.trace.export_chrome_trace``.
"""
import json
import os
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import flight as _flight
from metrics_tpu.obs import registry as _reg

#: event kinds rendered as instants, with the track they land on. ``None``
#: means "take the track from the event's ``metric`` field".
_INSTANT_TRACKS = {
    "dispatch": None,
    "retrace": None,
    "merge": None,
    "fused_launch": "fused",
    "fused_cache_miss": "fused",
    "fleet_route": None,
    "ckpt_save_begin": "ckpt",
    "ckpt_save_commit": "ckpt",
    "ckpt_restore": "ckpt",
}


def _scope_track(label: str) -> str:
    """``tm.update/BinaryAccuracy`` -> ``BinaryAccuracy``; ``tm.fused/step`` ->
    ``fused``; ``tm.collection.update`` -> ``collection``."""
    if label.startswith("tm."):
        label = label[3:]
    if "/" in label:
        op, owner = label.split("/", 1)
        return "fused" if op == "fused" else owner
    return label.split(".", 1)[0]


def chrome_trace_events(events: Optional[List[Dict[str, Any]]] = None) -> List[Dict[str, Any]]:
    """Flight events -> ``trace_event`` dicts (µs timestamps, one tid/track)."""
    if events is None:
        events = _flight.events()
    pid = os.getpid()
    tids: Dict[str, int] = {}
    out: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": "metrics_tpu"},
        }
    ]

    def tid_for(track: str) -> int:
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
            out.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": track},
                }
            )
        return tid

    #: coalesced ticks already given their single launch/device slice
    flow_ticks: set = set()

    for ev in events:
        kind = ev.get("kind")
        args = {
            k: v for k, v in ev.items() if k not in ("kind", "ts_us", "seq", "dur_us")
        }
        args["seq"] = ev.get("seq")
        if kind == "flow_complete":
            out.extend(_flow_events(ev, pid, tid_for, flow_ticks))
            continue
        if kind == "scope":
            label = ev.get("name", "tm.scope")
            out.append(
                {
                    "ph": "X",
                    "name": label,
                    "cat": "tm",
                    "ts": ev["ts_us"],
                    "dur": max(float(ev.get("dur_us", 0.0)), 0.001),
                    "pid": pid,
                    "tid": tid_for(_scope_track(label)),
                    "args": args,
                }
            )
            continue
        track = _INSTANT_TRACKS.get(kind)
        if track is None:
            track = str(ev.get("metric", kind))
        # two queues (or engines) sharing a metric class must not share a
        # track: suffix with the instance name whenever the event carries one
        instance = ev.get("queue") or ev.get("engine")
        if instance is not None:
            track = f"{track}/{instance}"
        out.append(
            {
                "ph": "i",
                "name": str(kind),
                "cat": "tm",
                "s": "t",  # thread-scoped instant
                "ts": ev["ts_us"],
                "pid": pid,
                "tid": tid_for(track),
                "args": args,
            }
        )
    return out


def _flow_events(
    ev: Dict[str, Any], pid: int, tid_for: Any, ticks_done: set
) -> List[Dict[str, Any]]:
    """One ``flow_complete`` flight event -> slices + flow-arrow events.

    Per flow: an enqueue slice on ``ingest/<queue>`` holding the arrow start
    (``ph s``); per coalesced tick (shared by every flow the launch served):
    ONE launch slice on ``launcher/<queue>`` and one device slice on
    ``compute/<queue>``; per flow again: a ``ph t`` step bound to the launch
    slice and a ``ph f`` finish bound to the device slice. Flows that never
    launched (degraded/dropped before dispatch) render their enqueue slice
    only — an arrow needs both ends.
    """
    queue = str(ev.get("queue", "?"))
    fid = ev.get("id")
    t_enq = ev.get("t_enq_us")
    if t_enq is None:
        return []
    out: List[Dict[str, Any]] = []
    enq_tid = tid_for(f"ingest/{queue}")
    args = {
        "flow_id": ev.get("flow_id"),
        "rows": ev.get("rows"),
        "streams": ev.get("streams"),
        "degraded": ev.get("degraded"),
        "dropped": ev.get("dropped"),
        "seq": ev.get("seq"),
        **{k: ev.get(k) for k in ("queue_wait_us", "coalesce_us", "compile_us",
                                  "launch_us", "device_us", "readback_us")},
    }
    queue_wait = float(ev.get("queue_wait_us") or 0.0)
    out.append(
        {
            "ph": "X", "name": "flow/enqueue", "cat": "flow",
            "ts": t_enq, "dur": max(queue_wait, 0.001),
            "pid": pid, "tid": enq_tid, "args": args,
        }
    )
    t_launch = ev.get("t_launch_us")
    t_dispatch = ev.get("t_dispatch_us")
    t_device = ev.get("t_device_us")
    tick = ev.get("tick")
    if fid is None or t_launch is None or t_dispatch is None or t_device is None:
        return out
    launch_tid = tid_for(f"launcher/{queue}")
    device_tid = tid_for(f"compute/{queue}")
    tick_key = (queue, tick)
    if tick_key not in ticks_done:
        ticks_done.add(tick_key)
        out.append(
            {
                "ph": "X", "name": "flow/launch", "cat": "flow",
                "ts": t_launch, "dur": max(t_dispatch - t_launch, 0.001),
                "pid": pid, "tid": launch_tid,
                "args": {"tick": tick, "queue": queue},
            }
        )
        out.append(
            {
                "ph": "X", "name": "flow/device", "cat": "flow",
                "ts": t_dispatch, "dur": max(t_device - t_dispatch, 0.001),
                "pid": pid, "tid": device_tid,
                "args": {"tick": tick, "queue": queue},
            }
        )
    arrow = {"name": "flow", "cat": "flow", "id": fid, "pid": pid}
    out.append({"ph": "s", "ts": t_enq, "tid": enq_tid, **arrow})
    out.append({"ph": "t", "ts": t_launch, "tid": launch_tid, **arrow})
    out.append({"ph": "f", "bp": "e", "ts": t_device, "tid": device_tid, **arrow})
    return out


def export_chrome_trace(
    path: str,
    events: Optional[List[Dict[str, Any]]] = None,
    include_registry: bool = True,
) -> Dict[str, Any]:
    """Write the trace JSON to ``path``; returns the written object.

    ``otherData`` carries the registry counter snapshot (when obs holds one)
    so a single file answers both "what happened when" and "how often".
    """
    trace_events = chrome_trace_events(events)
    obj: Dict[str, Any] = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "metrics_tpu.obs.trace"},
    }
    if include_registry:
        obj["otherData"]["registry"] = _reg.snapshot()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, default=str)
    return obj


def validate_chrome_trace(obj: Dict[str, Any]) -> int:
    """Structural check against the ``trace_event`` format; returns the event
    count. Raises ``ValueError`` naming the first offending event — used by the
    CI obs tier and ``bench.py --obs-trace`` to guarantee the exported file is
    Perfetto-loadable without eyeballing a UI.
    """
    if not isinstance(obj, dict) or not isinstance(obj.get("traceEvents"), list):
        raise ValueError("trace must be an object with a `traceEvents` list")
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if ph not in ("X", "i", "I", "M", "B", "E", "C", "s", "t", "f"):
            raise ValueError(f"traceEvents[{i}] has unsupported ph={ph!r}")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"traceEvents[{i}] missing string `name`")
        if not isinstance(ev.get("pid"), int) or not isinstance(ev.get("tid"), int):
            raise ValueError(f"traceEvents[{i}] missing integer pid/tid")
        if ph in ("X", "i", "I", "B", "E", "C", "s", "t", "f") and not isinstance(
            ev.get("ts"), (int, float)
        ):
            raise ValueError(f"traceEvents[{i}] ({ph}) missing numeric `ts`")
        if ph in ("s", "t", "f") and not isinstance(ev.get("id"), (int, str)):
            raise ValueError(
                f"traceEvents[{i}] ({ph}) flow event missing its `id` binding"
            )
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            raise ValueError(f"traceEvents[{i}] (X) missing numeric `dur`")
        if ph == "M" and "args" not in ev:
            raise ValueError(f"traceEvents[{i}] (M) missing `args`")
    return len(obj["traceEvents"])
