"""Perfetto / Chrome ``trace_event`` export of the ``tm.*`` runtime timeline.

Turns the flight-recorder window (``obs/flight.py``) into a JSON object-format
trace — ``{"traceEvents": [...], "displayTimeUnit": "ms", "otherData": ...}``
— loadable in ``chrome://tracing`` and https://ui.perfetto.dev, and
correlatable with a ``jax.profiler`` XProf capture of the same run: the host
slices here carry the same ``tm.update/<Metric>`` / ``tm.fused/step`` names as
the ``jax.named_scope`` annotations baked into the HLO.

Track model — one track per metric/engine:

- every ``scope`` flight event (a timed ``tm.*`` window from
  ``obs/scopes.py``) becomes a complete slice (``"ph": "X"``) on the track of
  the metric or engine that owns it (``tm.update/BinaryAccuracy`` → track
  ``BinaryAccuracy``, ``tm.fused/step`` → track ``fused``);
- point events (``dispatch``, ``retrace``, ``fused_cache_miss``,
  ``fleet_route``, ``merge``, ``ckpt_*``) become instants (``"ph": "i"``) on
  the owning track, with their structured fields — input avals, cache keys,
  routed rows, commit steps — in ``args`` where the Perfetto UI shows them on
  click;
- tracks are named via ``thread_name`` metadata events, so the timeline reads
  as one row per metric/engine rather than anonymous tids.

Naming note: the *module* ``metrics_tpu.obs.trace`` (this file) is the
exporter; the *attribute* ``metrics_tpu.obs.trace`` remains the XProf capture
context manager from ``obs/scopes.py`` for backward compatibility — use
``obs.export_chrome_trace(...)`` / ``obs.chrome_trace_events()`` (re-exported
at the package root) rather than ``obs.trace.export_chrome_trace``.
"""
import json
import os
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import flight as _flight
from metrics_tpu.obs import registry as _reg

#: event kinds rendered as instants, with the track they land on. ``None``
#: means "take the track from the event's ``metric`` field".
_INSTANT_TRACKS = {
    "dispatch": None,
    "retrace": None,
    "merge": None,
    "fused_launch": "fused",
    "fused_cache_miss": "fused",
    "fleet_route": None,
    "ckpt_save_begin": "ckpt",
    "ckpt_save_commit": "ckpt",
    "ckpt_restore": "ckpt",
}


def _scope_track(label: str) -> str:
    """``tm.update/BinaryAccuracy`` -> ``BinaryAccuracy``; ``tm.fused/step`` ->
    ``fused``; ``tm.collection.update`` -> ``collection``."""
    if label.startswith("tm."):
        label = label[3:]
    if "/" in label:
        op, owner = label.split("/", 1)
        return "fused" if op == "fused" else owner
    return label.split(".", 1)[0]


def chrome_trace_events(events: Optional[List[Dict[str, Any]]] = None) -> List[Dict[str, Any]]:
    """Flight events -> ``trace_event`` dicts (µs timestamps, one tid/track)."""
    if events is None:
        events = _flight.events()
    pid = os.getpid()
    tids: Dict[str, int] = {}
    out: List[Dict[str, Any]] = [
        {
            "ph": "M",
            "name": "process_name",
            "pid": pid,
            "tid": 0,
            "args": {"name": "metrics_tpu"},
        }
    ]

    def tid_for(track: str) -> int:
        tid = tids.get(track)
        if tid is None:
            tid = tids[track] = len(tids) + 1
            out.append(
                {
                    "ph": "M",
                    "name": "thread_name",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": track},
                }
            )
        return tid

    for ev in events:
        kind = ev.get("kind")
        args = {
            k: v for k, v in ev.items() if k not in ("kind", "ts_us", "seq", "dur_us")
        }
        args["seq"] = ev.get("seq")
        if kind == "scope":
            label = ev.get("name", "tm.scope")
            out.append(
                {
                    "ph": "X",
                    "name": label,
                    "cat": "tm",
                    "ts": ev["ts_us"],
                    "dur": max(float(ev.get("dur_us", 0.0)), 0.001),
                    "pid": pid,
                    "tid": tid_for(_scope_track(label)),
                    "args": args,
                }
            )
            continue
        track = _INSTANT_TRACKS.get(kind)
        if track is None:
            track = str(ev.get("metric", kind))
        out.append(
            {
                "ph": "i",
                "name": str(kind),
                "cat": "tm",
                "s": "t",  # thread-scoped instant
                "ts": ev["ts_us"],
                "pid": pid,
                "tid": tid_for(track),
                "args": args,
            }
        )
    return out


def export_chrome_trace(
    path: str,
    events: Optional[List[Dict[str, Any]]] = None,
    include_registry: bool = True,
) -> Dict[str, Any]:
    """Write the trace JSON to ``path``; returns the written object.

    ``otherData`` carries the registry counter snapshot (when obs holds one)
    so a single file answers both "what happened when" and "how often".
    """
    trace_events = chrome_trace_events(events)
    obj: Dict[str, Any] = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "metrics_tpu.obs.trace"},
    }
    if include_registry:
        obj["otherData"]["registry"] = _reg.snapshot()
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, default=str)
    return obj


def validate_chrome_trace(obj: Dict[str, Any]) -> int:
    """Structural check against the ``trace_event`` format; returns the event
    count. Raises ``ValueError`` naming the first offending event — used by the
    CI obs tier and ``bench.py --obs-trace`` to guarantee the exported file is
    Perfetto-loadable without eyeballing a UI.
    """
    if not isinstance(obj, dict) or not isinstance(obj.get("traceEvents"), list):
        raise ValueError("trace must be an object with a `traceEvents` list")
    for i, ev in enumerate(obj["traceEvents"]):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if ph not in ("X", "i", "I", "M", "B", "E", "C"):
            raise ValueError(f"traceEvents[{i}] has unsupported ph={ph!r}")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"traceEvents[{i}] missing string `name`")
        if not isinstance(ev.get("pid"), int) or not isinstance(ev.get("tid"), int):
            raise ValueError(f"traceEvents[{i}] missing integer pid/tid")
        if ph in ("X", "i", "I", "B", "E", "C") and not isinstance(
            ev.get("ts"), (int, float)
        ):
            raise ValueError(f"traceEvents[{i}] ({ph}) missing numeric `ts`")
        if ph == "X" and not isinstance(ev.get("dur"), (int, float)):
            raise ValueError(f"traceEvents[{i}] (X) missing numeric `dur`")
        if ph == "M" and "args" not in ev:
            raise ValueError(f"traceEvents[{i}] (M) missing `args`")
    return len(obj["traceEvents"])
