"""Process-global observability registry: per-metric counters and timers.

Off by default. The hot paths in ``core/metric.py`` and ``parallel/collective.py``
gate every registry touch behind a single module-attribute boolean check
(``if registry._ENABLED:``), so the disabled path costs one dict-free attribute
load and nothing else — no locks, no allocations, no device syncs (verified by
``tests/unittests/obs/test_obs.py::test_disabled_mode_writes_nothing`` and the
bench-parity criterion in ISSUE 1).

Counting semantics: counters count **host-level events**. A metric update that
runs eagerly counts once per call; the same update traced into a ``jit``/
``shard_map`` program counts once per *trace* (XLA executions are invisible to
host code). Retrace detection (``recompile.py``) exists precisely because the
trace-time view is the one that matters for compile storms.
"""
import json
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterator, Optional

# Single boolean the instrumented hot paths check. Module attribute (not a
# function) so the disabled cost is one LOAD_ATTR.
_ENABLED: bool = False


class _Stopwatch:
    """Result object of :func:`ObsRegistry.stopwatch` — ``elapsed`` in seconds."""

    __slots__ = ("elapsed", "_t0")

    def __init__(self) -> None:
        self.elapsed: float = 0.0
        self._t0: float = 0.0


class ObsRegistry:
    """Thread-safe counter/timer store keyed by ``(scope, name)``.

    ``scope`` is typically a metric class name (``"MulticlassAccuracy"``) or a
    subsystem (``"sync"``, ``"jax"``); ``name`` is the event (``"updates"``,
    ``"retraces"``, ``"bytes_gathered"``...). Timers accumulate
    ``{count, total_s, max_s}`` per key.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[tuple, float] = {}
        self._timers: Dict[tuple, Dict[str, float]] = {}
        # dirty flag: True once anything was recorded since the last clear().
        # The JSONL export uses it to report the gate state that was in effect
        # FOR the recorded counters, which may differ from the instantaneous
        # gate (a scoped `observe()` window that already exited).
        self._recorded = False

    # ----------------------------------------------------------- counters

    def inc(self, scope: str, name: str, value: float = 1) -> None:
        key = (scope, name)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value
            self._recorded = True

    def get(self, scope: str, name: str, default: float = 0) -> float:
        return self._counters.get((scope, name), default)

    # ------------------------------------------------------------- timers

    def observe_duration(self, scope: str, name: str, seconds: float) -> None:
        key = (scope, name)
        with self._lock:
            t = self._timers.setdefault(key, {"count": 0, "total_s": 0.0, "max_s": 0.0})
            t["count"] += 1
            t["total_s"] += seconds
            t["max_s"] = max(t["max_s"], seconds)
            self._recorded = True

    def recorded(self) -> bool:
        """True once any counter/timer write landed since the last clear()."""
        return self._recorded

    @contextmanager
    def stopwatch(self, scope: str, name: str) -> Iterator[_Stopwatch]:
        """Always measures wall time (``sw.elapsed``); records into the registry
        only when obs is enabled, so callers (e.g. ``bench.py``) can time through
        one code path whether or not observability is on."""
        sw = _Stopwatch()
        sw._t0 = time.perf_counter()
        try:
            yield sw
        finally:
            sw.elapsed = time.perf_counter() - sw._t0
            if _ENABLED:
                self.observe_duration(scope, name, sw.elapsed)

    # ------------------------------------------------------------ export

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Nested ``{scope: {name: value}}`` view; timers appear under
        ``{scope: {name: {count, total_s, max_s}}}``."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            for (scope, name), value in self._counters.items():
                out.setdefault(scope, {})[name] = value
            for (scope, name), t in self._timers.items():
                out.setdefault(scope, {})[name] = dict(t)
        return out

    def clear(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._recorded = False


#: The process-global registry instance the instrumented runtime writes into.
REGISTRY = ObsRegistry()

_compile_listener_registered = False


def _register_compile_listener() -> None:
    """Best-effort hook on jax.monitoring compile events (idempotent).

    The listener itself is gated on ``_ENABLED`` so a later ``disable()`` stops
    the accounting without touching other libraries' listeners."""
    global _compile_listener_registered
    if _compile_listener_registered:
        return
    try:
        from jax import monitoring

        def _on_duration(event: str, duration: float, **kw: Any) -> None:
            if _ENABLED and "compile" in event:
                REGISTRY.inc("jax", "compile_events")
                REGISTRY.observe_duration("jax", "compile_time", duration)

        monitoring.register_event_duration_secs_listener(_on_duration)
        _compile_listener_registered = True
    except Exception:  # noqa: BLE001 — observability must never break the runtime
        pass


def enable(clear: bool = False) -> None:
    """Turn the instrumentation layer on (counters, scopes, retrace detection)."""
    global _ENABLED
    if clear:
        REGISTRY.clear()
    _register_compile_listener()
    _ENABLED = True


def disable() -> None:
    """Return to the zero-overhead default."""
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


@contextmanager
def observe(clear: bool = False) -> Iterator[ObsRegistry]:
    """Scoped ``enable()``: restores the previous on/off state on exit."""
    global _ENABLED
    prev = _ENABLED
    enable(clear=clear)
    try:
        yield REGISTRY
    finally:
        _ENABLED = prev


def snapshot() -> Dict[str, Dict[str, Any]]:
    return REGISTRY.snapshot()


def snapshot_json() -> str:
    return json.dumps(REGISTRY.snapshot(), sort_keys=True)
