"""Self-telemetry: latency percentiles, HBM watermark, declarative SLO budgets.

The obs registry answers "how many"; this module answers "how slow, at what
tail". It dogfoods the repo's own :class:`~metrics_tpu.sketches.QuantileSketch`
— per ``(op, metric)`` update/compute latency lands in a mergeable DDSketch
with O(1) state (~16 KB per tracked key) instead of an unbounded list, so a
week-long serving job holds the same memory as a minute-long one and the
percentiles carry the sketch's relative-error certificate.

Recording path: ``obs/scopes.py`` times every ``tm.*`` window when a monitor
is active and feeds :meth:`HealthMonitor.observe_scope`. Observations buffer
in plain Python lists and flush into the sketch in **fixed-size batches**
(``flush_every``), for two reasons: one vectorized sketch update per batch
instead of one XLA dispatch per metric update, and a *constant* batch shape so
the self-telemetry never triggers the retrace detector it lives next to
(residual flushes pad with NaN — the sketch counts NaNs outside the ranks by
construction). While flushing, the obs gate is suppressed so self-telemetry
never pollutes the counters, scopes, flight ring, or its own latency stream.

The HBM watermark samples ``device.memory_stats()['bytes_in_use']`` (TPU
backends; CPU reports nothing) every ``hbm_sample_every`` observations, plus
any explicit :func:`observe_state_bytes` calls, and keeps the max.

SLO budgets are declarative: :func:`set_slo` names the budget, \
:func:`check_slos` evaluates it against the registry/sketches and reacts per
the configured action (``"warn"`` → :class:`SLOViolationWarning`, ``"raise"``
→ :class:`SLOBudgetExceeded`, or any callable receiving the violation list).

Zero-overhead contract: module global ``_MONITOR`` stays ``None`` until
:func:`enable` — no sketches, no buffers, no budgets are allocated before
then, and the instrumented paths check ``_MONITOR is not None`` from inside
existing ``registry._ENABLED`` blocks only.
"""
import threading
import time
import warnings
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

from metrics_tpu.obs import registry as _reg

_MONITOR: Optional["HealthMonitor"] = None

#: scope ops whose latency is sketched per metric
_TRACKED_OPS = ("update", "compute", "forward", "fused")


class SLOViolationWarning(RuntimeWarning):
    """Named warning for a breached SLO budget (action="warn")."""


class SLOBudgetExceeded(RuntimeError):
    """Raised for a breached SLO budget when action="raise"."""


class SLOBudget:
    """One declarative service-level budget.

    Args:
        max_launches_per_step: ceiling on XLA launches per step, measured off
            the summed ``dispatches`` counters (requires ``steps`` at check
            time).
        max_retraces_per_window: ceiling on retrace events (instance retraces
            + class-level signature churn) accumulated since the last check —
            each ``check_slos`` call closes one window.
        p99_update_latency_ms: ceiling on any single metric's p99 update
            latency, from the health sketches.
        max_nonfinite_rows: ceiling on total NaN/Inf input rows tallied by
            ``Metric(nan_policy=...)`` quarantines (summed ``nonfinite_rows``
            counters across scopes) — an input-poisoning SLO.
        max_queue_depth: ceiling on the deepest ingest staging backlog across
            active ``serve.IngestQueue`` instances at check time — a producer
            outrunning the tick thread is a serving incident before it is a
            data-loss incident.
        p99_ingest_latency_ms: ceiling on any queue's p99 enqueue→applied
            latency (the ``ingest/<queue>`` health sketches) — the freshness
            SLO of the async ingestion tier.
        max_cold_compiles: ceiling on *true* XLA compiles observed since the
            excache stats were last cleared (``serve.excache.stats()
            ["compiles"]`` — persistent-cache misses). A pre-warmed replica
            budgets 0 here: its first request must be served entirely from
            the seeded executable caches.
        p99_flow_latency_ms: ceiling on any traced flow population's p99
            end-to-end latency (the ``flow/<queue>`` and per-tenant
            ``flow/<queue>/<stream>`` sketches fed by ``obs.flow``) — the
            request-level SLO of the tracing tier.
        action: ``"warn"`` | ``"raise"`` | callable(list_of_violations).
    """

    def __init__(
        self,
        max_launches_per_step: Optional[float] = None,
        max_retraces_per_window: Optional[int] = None,
        p99_update_latency_ms: Optional[float] = None,
        max_nonfinite_rows: Optional[int] = None,
        max_queue_depth: Optional[int] = None,
        p99_ingest_latency_ms: Optional[float] = None,
        max_cold_compiles: Optional[int] = None,
        p99_flow_latency_ms: Optional[float] = None,
        action: Union[str, Callable[[List[Dict[str, Any]]], None]] = "warn",
    ) -> None:
        if isinstance(action, str) and action not in ("warn", "raise"):
            raise ValueError(f"SLO action must be 'warn', 'raise' or a callable, got {action!r}")
        self.max_launches_per_step = max_launches_per_step
        self.max_retraces_per_window = max_retraces_per_window
        self.p99_update_latency_ms = p99_update_latency_ms
        self.max_nonfinite_rows = max_nonfinite_rows
        self.max_queue_depth = max_queue_depth
        self.p99_ingest_latency_ms = p99_ingest_latency_ms
        self.max_cold_compiles = max_cold_compiles
        self.p99_flow_latency_ms = p99_flow_latency_ms
        self.action = action


class HealthMonitor:
    """Latency sketches + HBM watermark + SLO state (see module docstring)."""

    def __init__(
        self,
        flush_every: int = 256,
        relative_error: float = 0.01,
        quantiles: Tuple[float, ...] = (0.5, 0.9, 0.99),
        hbm_sample_every: int = 64,
    ) -> None:
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.flush_every = int(flush_every)
        self.relative_error = float(relative_error)
        self.quantiles = tuple(float(q) for q in quantiles)
        if 0.99 not in self.quantiles:
            self.quantiles = self.quantiles + (0.99,)
        self.hbm_sample_every = int(hbm_sample_every)
        self._lock = threading.RLock()
        self._in_self = False  # reentrancy guard: sketch updates re-enter scopes
        # key -> (sketch instance, state pytree, observation count)
        self._sketches: Dict[Tuple[str, str], List[Any]] = {}
        self._buffers: Dict[Tuple[str, str], List[float]] = {}
        self._obs_count = 0
        self.hbm_watermark_bytes: Optional[int] = None
        self.budget: Optional[SLOBudget] = None
        self._window_base: Dict[str, float] = {}
        self._mark_window()

    # ------------------------------------------------------------ recording

    def observe_scope(self, label: str, seconds: float) -> None:
        """One timed ``tm.*`` window; called from ``obs/scopes.py``."""
        if self._in_self or not label.startswith("tm."):
            return
        body = label[3:]
        op, _, owner = body.partition("/")
        if op not in _TRACKED_OPS:
            return
        self.observe_latency(op, owner or op, seconds)

    def observe_latency(self, op: str, name: str, seconds: float) -> None:
        if self._in_self:
            return
        key = (op, name)
        with self._lock:
            if self._in_self:
                return
            buf = self._buffers.setdefault(key, [])
            buf.append(seconds * 1e6)  # sketch in microseconds
            self._obs_count += 1
            sample_hbm = self._obs_count % self.hbm_sample_every == 0
            flush = len(buf) >= self.flush_every
            if flush:
                self._flush_locked(key)
        if sample_hbm:
            self._sample_hbm()

    def _sketch_for(self, key: Tuple[str, str]) -> List[Any]:
        entry = self._sketches.get(key)
        if entry is None:
            from metrics_tpu.sketches import QuantileSketch

            sk = QuantileSketch(
                relative_error=self.relative_error,
                quantiles=self.quantiles,
                min_value=1e-3,  # 1 nanosecond, in µs units
            )
            entry = self._sketches[key] = [sk, sk.init_state(), 0]
        return entry

    def _flush_locked(self, key: Tuple[str, str]) -> None:
        """Fold the buffered batch into the sketch — fixed shape, obs-gated off.

        Pads the residual with NaN so every flush compiles against ONE batch
        shape (NaNs are tallied outside the quantile ranks by the sketch).
        """
        buf = self._buffers.get(key)
        if not buf:
            return
        import jax.numpy as jnp

        batch = buf[: self.flush_every]
        n = len(batch)
        if n < self.flush_every:
            batch = batch + [float("nan")] * (self.flush_every - n)
        del buf[:n]
        entry = self._sketch_for(key)
        sk, state, count = entry
        prev = _reg._ENABLED
        _reg._ENABLED = False  # self-telemetry must not observe itself
        self._in_self = True
        try:
            entry[1] = sk.local_update(state, jnp.asarray(batch, jnp.float32))
            entry[2] = count + n
        finally:
            self._in_self = False
            _reg._ENABLED = prev

    def _sample_hbm(self) -> None:
        try:
            import jax

            stats = jax.local_devices()[0].memory_stats()
            bytes_in_use = (stats or {}).get("bytes_in_use")
        except Exception:  # noqa: BLE001 — backends without memory stats
            bytes_in_use = None
        if bytes_in_use is not None:
            self.note_hbm(int(bytes_in_use))

    def note_hbm(self, nbytes: int) -> None:
        with self._lock:
            if self.hbm_watermark_bytes is None or nbytes > self.hbm_watermark_bytes:
                self.hbm_watermark_bytes = int(nbytes)

    # ------------------------------------------------------------ reporting

    def report(self) -> Dict[str, Any]:
        """Flush residuals and return percentiles + watermark as one dict."""
        with self._lock:
            for key in list(self._buffers):
                self._flush_locked(key)
            latency: Dict[str, Any] = {}
            prev = _reg._ENABLED
            _reg._ENABLED = False
            self._in_self = True
            try:
                for (op, name), (sk, state, count) in sorted(self._sketches.items()):
                    if count == 0:
                        continue
                    out = sk.compute_from(state)
                    row = {"count": int(count)}
                    for q, v, c in zip(
                        sk.quantiles, out["quantiles"].tolist(), out["certified"].tolist()
                    ):
                        row[f"p{round(q * 100):d}_us"] = round(float(v), 3)
                        row[f"p{round(q * 100):d}_certified"] = bool(c)
                    latency[f"{op}/{name}"] = row
            finally:
                self._in_self = False
                _reg._ENABLED = prev
            return {
                "latency_us": latency,
                "hbm_watermark_bytes": self.hbm_watermark_bytes,
                "relative_error": self.relative_error,
                "flush_every": self.flush_every,
            }

    def export_sketches(self) -> Dict[str, Any]:
        """Flush residuals and export each latency sketch as mergeable state.

        The cross-host currency of ``obs/aggregate``: per ``op/metric`` key,
        the sketch construction params plus its int32 state leaves as plain
        Python lists — JSON-serializable, and (every leaf being sum-reduced)
        mergeable *exactly* by elementwise addition on whichever host
        reconstructs the sketch. Same gate suppression as :meth:`report`.
        """
        with self._lock:
            for key in list(self._buffers):
                self._flush_locked(key)
            out: Dict[str, Any] = {}
            prev = _reg._ENABLED
            _reg._ENABLED = False
            self._in_self = True
            try:
                for (op, name), (sk, state, count) in sorted(self._sketches.items()):
                    if count == 0:
                        continue
                    out[f"{op}/{name}"] = {
                        "params": {
                            "relative_error": sk.relative_error,
                            "bits": sk.bits,
                            "min_value": sk.min_value,
                            "quantiles": list(sk.quantiles),
                        },
                        "state": {k: v.tolist() for k, v in state.items()},
                        "count": int(count),
                    }
            finally:
                self._in_self = False
                _reg._ENABLED = prev
            return out

    # ------------------------------------------------------------------ SLO

    def _mark_window(self) -> None:
        snap = _reg.snapshot()
        total = 0.0
        for counters in snap.values():
            for name in ("retraces", "retrace_signatures"):
                v = counters.get(name)
                if isinstance(v, (int, float)):
                    total += v
        self._window_base = {"retraces": total, "t": time.time()}

    def check_slos(self, steps: Optional[int] = None) -> List[Dict[str, Any]]:
        """Evaluate the configured budget; returns (and reacts to) violations.

        Each call closes the retrace window — the next check counts retraces
        accumulated from now.
        """
        budget = self.budget
        if budget is None:
            return []
        violations: List[Dict[str, Any]] = []
        snap = _reg.snapshot()

        if budget.max_launches_per_step is not None and steps:
            launches = sum(
                counters.get("dispatches", 0)
                for counters in snap.values()
                if isinstance(counters.get("dispatches", 0), (int, float))
            )
            per_step = launches / steps
            if per_step > budget.max_launches_per_step:
                violations.append(
                    {
                        "slo": "max_launches_per_step",
                        "budget": budget.max_launches_per_step,
                        "measured": per_step,
                        "detail": f"{launches:.0f} launches over {steps} steps",
                    }
                )

        if budget.max_retraces_per_window is not None:
            total = 0.0
            for counters in snap.values():
                for name in ("retraces", "retrace_signatures"):
                    v = counters.get(name)
                    if isinstance(v, (int, float)):
                        total += v
            window = total - self._window_base.get("retraces", 0.0)
            if window > budget.max_retraces_per_window:
                violations.append(
                    {
                        "slo": "max_retraces_per_window",
                        "budget": budget.max_retraces_per_window,
                        "measured": window,
                        "detail": f"window opened {time.time() - self._window_base['t']:.1f}s ago",
                    }
                )
            self._mark_window()

        if budget.max_nonfinite_rows is not None:
            poisoned = sum(
                counters.get("nonfinite_rows", 0)
                for counters in snap.values()
                if isinstance(counters.get("nonfinite_rows", 0), (int, float))
            )
            if poisoned > budget.max_nonfinite_rows:
                violations.append(
                    {
                        "slo": "max_nonfinite_rows",
                        "budget": budget.max_nonfinite_rows,
                        "measured": poisoned,
                        "detail": "NaN/Inf input rows tallied by nan_policy quarantines",
                    }
                )

        if budget.p99_update_latency_ms is not None:
            latency = self.report()["latency_us"]
            for key, row in latency.items():
                if not key.startswith("update/"):
                    continue
                p99_ms = row.get("p99_us", float("nan")) / 1000.0
                if p99_ms > budget.p99_update_latency_ms:
                    violations.append(
                        {
                            "slo": "p99_update_latency_ms",
                            "budget": budget.p99_update_latency_ms,
                            "measured": round(p99_ms, 4),
                            "detail": f"metric {key.split('/', 1)[1]}"
                            + ("" if row.get("p99_certified") else " (uncertified edge-bin rank)"),
                        }
                    )

        if budget.p99_ingest_latency_ms is not None:
            latency = self.report()["latency_us"]
            for key, row in latency.items():
                if not key.startswith("ingest/"):
                    continue
                p99_ms = row.get("p99_us", float("nan")) / 1000.0
                if p99_ms > budget.p99_ingest_latency_ms:
                    violations.append(
                        {
                            "slo": "p99_ingest_latency_ms",
                            "budget": budget.p99_ingest_latency_ms,
                            "measured": round(p99_ms, 4),
                            "detail": f"queue {key.split('/', 1)[1]} enqueue->applied"
                            + ("" if row.get("p99_certified") else " (uncertified edge-bin rank)"),
                        }
                    )

        if budget.p99_flow_latency_ms is not None:
            latency = self.report()["latency_us"]
            for key, row in latency.items():
                if not key.startswith("flow/"):
                    continue
                p99_ms = row.get("p99_us", float("nan")) / 1000.0
                if p99_ms > budget.p99_flow_latency_ms:
                    violations.append(
                        {
                            "slo": "p99_flow_latency_ms",
                            "budget": budget.p99_flow_latency_ms,
                            "measured": round(p99_ms, 4),
                            "detail": f"flow {key.split('/', 1)[1]} end-to-end"
                            + ("" if row.get("p99_certified") else " (uncertified edge-bin rank)"),
                        }
                    )

        if budget.max_queue_depth is not None:
            # pulled on demand, never from a hot path: the ingest tier only
            # participates once its module has been imported by the app
            import sys as _sys

            _ingest = _sys.modules.get("metrics_tpu.serve.ingest")
            if _ingest is not None:
                depth = _ingest.max_queue_depth()
                if depth > budget.max_queue_depth:
                    violations.append(
                        {
                            "slo": "max_queue_depth",
                            "budget": budget.max_queue_depth,
                            "measured": depth,
                            "detail": "deepest staging backlog across active"
                            " serve.IngestQueue instances",
                        }
                    )

        if budget.max_cold_compiles is not None:
            # same on-demand discipline: the excache tier only participates
            # once the app imported serve/excache.py
            import sys as _sys

            _excache = _sys.modules.get("metrics_tpu.serve.excache")
            if _excache is not None:
                compiles = _excache.stats()["compiles"]
                if compiles > budget.max_cold_compiles:
                    violations.append(
                        {
                            "slo": "max_cold_compiles",
                            "budget": budget.max_cold_compiles,
                            "measured": compiles,
                            "detail": "true XLA compiles (persistent-cache"
                            " misses) since excache stats were cleared",
                        }
                    )

        if violations:
            self._react(budget, violations)
        return violations

    @staticmethod
    def _react(budget: SLOBudget, violations: List[Dict[str, Any]]) -> None:
        if callable(budget.action):
            budget.action(violations)
            return
        msg = "; ".join(
            f"{v['slo']}: measured {v['measured']} > budget {v['budget']} ({v['detail']})"
            for v in violations
        )
        if budget.action == "raise":
            raise SLOBudgetExceeded(f"metrics_tpu.obs.health SLO breached — {msg}")
        warnings.warn(
            f"metrics_tpu.obs.health SLO breached — {msg}",
            SLOViolationWarning,
            stacklevel=3,
        )


# ----------------------------------------------------------- module facade


def enable(
    flush_every: int = 256,
    relative_error: float = 0.01,
    hbm_sample_every: int = 64,
    enable_obs: bool = True,
) -> "HealthMonitor":
    """Allocate and activate the monitor (idempotent: replaces any previous)."""
    global _MONITOR
    _MONITOR = HealthMonitor(
        flush_every=flush_every,
        relative_error=relative_error,
        hbm_sample_every=hbm_sample_every,
    )
    if enable_obs:
        _reg.enable()
    return _MONITOR


def disable() -> None:
    global _MONITOR
    _MONITOR = None


def active() -> bool:
    return _MONITOR is not None


def monitor() -> Optional["HealthMonitor"]:
    return _MONITOR


def set_slo(**kwargs: Any) -> SLOBudget:
    """Declare the SLO budget on the active monitor (see :class:`SLOBudget`)."""
    if _MONITOR is None:
        raise RuntimeError("obs.health.set_slo requires an enabled monitor (health.enable())")
    budget = kwargs.pop("budget", None)
    if budget is None:
        budget = SLOBudget(**kwargs)
    _MONITOR.budget = budget
    _MONITOR._mark_window()
    return budget


def check_slos(steps: Optional[int] = None) -> List[Dict[str, Any]]:
    return _MONITOR.check_slos(steps=steps) if _MONITOR is not None else []


def report() -> Dict[str, Any]:
    return _MONITOR.report() if _MONITOR is not None else {}


def export_sketches() -> Dict[str, Any]:
    return _MONITOR.export_sketches() if _MONITOR is not None else {}


def observe_state_bytes(metric: Any) -> None:
    """Explicitly fold a metric's registered-state bytes into the watermark —
    the deterministic fallback for backends without ``memory_stats`` (CPU)."""
    if _MONITOR is None:
        return
    try:
        nbytes = metric.state_report()["total_nbytes"]
    except Exception:  # noqa: BLE001
        return
    _MONITOR.note_hbm(int(nbytes))
