"""tmflow: end-to-end causal request tracing with per-tenant attribution.

The serving stack's telemetry is per-subsystem — obs counters, flight events
and health sketches each see one hop. This module is the composition layer:
one **flow ID** minted per batch at ``IngestQueue.enqueue()`` (and at
synchronous ``update()``/``forward()`` when tracing is on) that follows the
batch through tick coalescing, the fused/fleet launch, device completion and
the checkpoint that captured it — so "why was THIS batch slow?" and "which
tenant is eating the tick budget?" have answers.

Flow lifecycle (each stage measured in µs on the ``perf_counter`` timebase the
flight recorder shares)::

    enqueue ──queue_wait──► drain ──coalesce──► launch ┬─compile─┐
                                                       └─launch──┴► dispatch
    dispatch ──device──► block_until_ready    compute() ──readback──► host

- ``queue_wait``: staged in the ingest ring (0 for synchronous flows);
- ``coalesce``: tick planning — signature split, state gather, cache lookup;
- ``compile``: AOT lower+compile when the launch missed its executable cache
  (0 on a hit);
- ``launch``: host-side dispatch of the compiled call, compile excluded;
- ``device``: dispatch return → ``block_until_ready`` on the returned state
  buffers, observed by a dedicated **completion-watcher** thread so host
  dispatch time and device execution time split cleanly;
- ``readback``: the ``compute()`` host transfer, stamped onto recently
  completed flows of the same queue.

Fan-in: one coalesced tick launch serves many flows; every flow dispatched by
the same launch shares a ``tick`` id, rendered as a single launch slice in the
Perfetto export (flow arrows from each enqueue slice) and as a ``tick`` span
holding one link per contained flow in :func:`export_spans`.

Gating contract (the single-boolean rule of ``registry.py``): every call site
lives inside an existing ``if registry._ENABLED:`` block and additionally
checks ``flow._TRACER is not None`` — nothing here allocates, locks, or runs
until :func:`enable` builds the tracer, and sampling (``sample_rate=N``)
traces 1-in-N flows so production can keep the tracer on. Flow events ride
the existing flight ring (GIL-atomic appends), flow latencies feed the
existing health ``QuantileSketch`` tier (``flow/<queue>`` end-to-end,
``flow/<queue>/<stream>`` per tenant, ``flow_stage/<stage>`` per stage), and
``obs.prom.render`` exposes ``tm_flow_*`` families off the same state.
"""
import hashlib
import itertools
import queue as _queue_mod
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from metrics_tpu.obs import flight as _flight
from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _reg
from metrics_tpu.utils.concurrency import locked_by, thread_role

__all__ = [
    "FlowTracer",
    "active",
    "current",
    "disable",
    "drain_for_ckpt",
    "enable",
    "export_spans",
    "records",
    "stats",
    "tracer",
    "validate_spans",
    "wait_idle",
]

#: ordered stage vocabulary of the latency breakdown (µs each)
STAGES = ("queue_wait", "coalesce", "compile", "launch", "device", "readback")

#: the tracer itself. ``None`` == tracing off == nothing allocated; hot paths
#: gate on ``_TRACER is not None`` inside their existing obs-enabled blocks.
_TRACER: Optional["FlowTracer"] = None

_ID_SEQ = itertools.count(1)

#: thread-local ambient-flow stack: the degraded/eager re-entry paths push the
#: originating flow here so the fused/fleet engines attribute their events to
#: it instead of minting a second flow for the same batch.
_TLS = threading.local()


def current() -> Optional["_Flow"]:
    """The ambient flow of this thread (innermost), or ``None``."""
    stack = getattr(_TLS, "stack", None)
    return stack[-1] if stack else None


def _push(fl: "_Flow") -> None:
    stack = getattr(_TLS, "stack", None)
    if stack is None:
        stack = _TLS.stack = []
    stack.append(fl)


def _pop() -> None:
    stack = getattr(_TLS, "stack", None)
    if stack:
        stack.pop()


def host_stream_ids(stream_ids: Any) -> Tuple[int, ...]:
    """Best-effort unique host ints from a ``stream_ids=`` argument.

    Tracers, abstract values, and exotic dtypes all degrade to ``()`` — the
    attribution is telemetry, never a correctness dependency.
    """
    if stream_ids is None:
        return ()
    try:
        import numpy as np

        arr = np.asarray(stream_ids)
        if arr.dtype.kind not in ("i", "u") or arr.ndim != 1 or not arr.size:
            return ()
        return tuple(int(s) for s in np.unique(arr)[:64])
    except Exception:  # noqa: BLE001 — attribution is best-effort by contract
        return ()


def _rows_of(args: Tuple, kwargs: Dict) -> int:
    for value in itertools.chain(args, kwargs.values()):
        shape = getattr(value, "shape", None)
        if shape:
            try:
                return int(shape[0])
            except Exception:  # noqa: BLE001 — symbolic dims
                return 1
    return 1


def _leaves_ready(leaves: List[Any]) -> bool:
    """True when every launch output is already materialized.

    ``jax.Array.is_ready()`` is a non-blocking future query; host leaves
    (numpy, scalars) have no such method and are ready by construction. Any
    probe failure routes to the watcher path, which is always correct.
    """
    try:
        for leaf in leaves:
            is_ready = getattr(leaf, "is_ready", None)
            if is_ready is not None and not is_ready():
                return False
    except Exception:  # noqa: BLE001 — donated/deleted buffers raise here
        return False
    return True


class _Flow:
    """One traced batch: identity, µs stamps, and attribution fields.

    Mutated only through :class:`FlowTracer` methods (pre-dispatch stamps run
    on the producing thread, the close runs on the watcher thread under the
    tracer lock — ``closed`` flips exactly once).
    """

    __slots__ = (
        "trace_id", "seq", "queue", "target_id", "sync", "rows", "streams",
        "tick", "t_enq", "t_drain", "t_launch", "t_dispatch", "t_device",
        "compile_us", "readback_us", "degraded", "dropped", "dispatched",
        "closed",
    )

    def __init__(self, trace_id: str, seq: int, queue: str, target_id: int,
                 sync: bool, rows: int, streams: Tuple[int, ...]) -> None:
        self.trace_id = trace_id
        self.seq = seq
        self.queue = queue
        self.target_id = target_id
        self.sync = sync
        self.rows = rows
        self.streams = streams
        self.tick: Optional[int] = None
        now = _flight._now_us()
        self.t_enq = now
        # synchronous flows never stage: queue_wait/coalesce start collapsed
        self.t_drain: Optional[float] = now if sync else None
        self.t_launch: Optional[float] = None
        self.t_dispatch: Optional[float] = None
        self.t_device: Optional[float] = None
        self.compile_us = 0.0
        self.readback_us = 0.0
        self.degraded = False
        self.dropped = False
        self.dispatched = False
        self.closed = False

    @property
    def flow_id(self) -> str:
        return self.trace_id

    def breakdown_us(self) -> Dict[str, float]:
        """The six-stage latency split; unreached stages report 0."""
        out = dict.fromkeys(STAGES, 0.0)
        if self.t_drain is not None:
            out["queue_wait"] = max(self.t_drain - self.t_enq, 0.0)
        if self.t_launch is not None and self.t_drain is not None:
            out["coalesce"] = max(self.t_launch - self.t_drain, 0.0)
        out["compile"] = self.compile_us
        if self.t_dispatch is not None and self.t_launch is not None:
            out["launch"] = max(self.t_dispatch - self.t_launch - self.compile_us, 0.0)
        if self.t_device is not None and self.t_dispatch is not None:
            out["device"] = max(self.t_device - self.t_dispatch, 0.0)
        out["readback"] = self.readback_us
        return out

    def end_us(self) -> float:
        for ts in (self.t_device, self.t_dispatch, self.t_launch, self.t_drain):
            if ts is not None:
                return ts
        return self.t_enq


class FlowTracer:
    """Flow table + completion watcher + rollup feeds (see module docstring).

    Args:
        sample_rate: trace 1-in-N minted flows (1 = every flow); sampled-out
            batches cost one counter increment and mint nothing.
        capacity: completed-flow records retained for the exporters (a bounded
            deque — the same last-K discipline as the flight ring).
    """

    def __init__(self, sample_rate: int = 1, capacity: int = 1024) -> None:
        if sample_rate < 1:
            raise ValueError(f"sample_rate must be >= 1, got {sample_rate}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.sample_rate = int(sample_rate)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._open: Dict[str, _Flow] = {}
        self._closed: List[_Flow] = []
        self._pending_readback: Dict[str, List[_Flow]] = {}
        self._since_ckpt: Dict[int, List[str]] = {}
        self._mint_seq = itertools.count()
        self._tick_seq = itertools.count(1)
        self._in_flight = 0  # dispatched work items the watcher has not closed
        #: wall-clock anchor pairing the µs perf_counter timebase with unix
        #: time, so span exports carry absolute nanos
        self.anchor = (time.time(), _flight._now_us())
        self.counts: Dict[str, int] = {
            "minted": 0, "sampled_out": 0, "completed": 0,
            "degraded": 0, "dropped": 0,
        }
        self._work: "_queue_mod.SimpleQueue" = _queue_mod.SimpleQueue()
        self._watcher = threading.Thread(
            target=self._watch, name="tm-flow-watcher", daemon=True
        )
        self._watcher.start()

    # ------------------------------------------------------------- minting

    def mint(self, queue: str, target_id: int, rows: int = 1,
             streams: Tuple[int, ...] = (), sync: bool = False) -> Optional[_Flow]:
        """Mint one flow (or ``None`` when sampled out). Records ``flow_begin``."""
        if (next(self._mint_seq) % self.sample_rate) != 0:
            with self._lock:
                self.counts["sampled_out"] += 1
            return None
        seq = next(_ID_SEQ)
        trace_id = f"{seq:016x}{id(self) & 0xFFFFFFFFFFFFFFFF:016x}"
        fl = _Flow(trace_id, seq, queue, target_id, sync, rows, streams)
        with self._lock:
            self.counts["minted"] += 1
            self._open[trace_id] = fl
        if _flight._RING is not None:
            _flight.record("flow_begin", ts_us=fl.t_enq, flow_id=trace_id,
                           id=seq, queue=queue, rows=rows, sync=sync)
        return fl

    def open_sync(self, queue: str, target_id: int, args: Tuple = (),
                  kwargs: Optional[Dict] = None) -> Optional[_Flow]:
        """Mint + make current for a synchronous ``update()``/``forward()``.

        Returns ``None`` when an ambient flow already covers this call (the
        ingest degrade/eager re-entry) or when sampled out.
        """
        if current() is not None:
            return None
        kwargs = kwargs or {}
        fl = self.mint(
            queue, target_id, rows=_rows_of(args, kwargs),
            streams=host_stream_ids(kwargs.get("stream_ids")), sync=True,
        )
        if fl is not None:
            _push(fl)
        return fl

    def close_sync(self, fl: _Flow) -> None:
        """End an :meth:`open_sync` scope; closes the flow unless the watcher
        now owns it (a successful launch handed it off)."""
        _pop()
        if not fl.dispatched and not fl.closed:
            with self._lock:
                self._close_locked(fl)

    # -------------------------------------------------------------- stamps

    def stamp_drain(self, flows: Sequence[_Flow]) -> None:
        now = _flight._now_us()
        for fl in flows:
            fl.t_drain = now

    def stamp_launch(self, flows: Sequence[_Flow]) -> None:
        now = _flight._now_us()
        for fl in flows:
            fl.t_launch = now

    def add_compile(self, flows: Sequence[_Flow], dur_us: float) -> None:
        for fl in flows:
            fl.compile_us += float(dur_us)

    def attribute_streams(self, fl: _Flow, streams: Iterable[int]) -> None:
        merged = set(fl.streams)
        merged.update(int(s) for s in streams)
        fl.streams = tuple(sorted(merged))[:64]

    # ------------------------------------------------------------ handoff

    def dispatch(self, flows: Sequence[_Flow], leaves: List[Any]) -> None:
        """Stamp host-dispatch completion and hand the flows to the watcher,
        which timestamps device completion via ``block_until_ready``."""
        if not flows:
            return
        now = _flight._now_us()
        tick = next(self._tick_seq)
        for fl in flows:
            fl.t_dispatch = now
            fl.tick = tick
            fl.dispatched = True
        if _leaves_ready(leaves):
            # Synchronous-ish backends (CPU, eager) finish the launch before
            # dispatch runs; closing inline skips the watcher handoff — two
            # context switches per launch that dominate on busy hosts. The
            # device stamp is taken now, so the device stage reads ~0, which
            # is what an already-complete launch means.
            done = _flight._now_us()
            with self._lock:
                for fl in flows:
                    fl.t_device = done
                    self._close_locked(fl)
            return
        with self._lock:
            self._in_flight += 1
        self._work.put((tuple(flows), leaves))

    @thread_role("tm-flow-watcher")
    def _watch(self) -> None:
        """Completion-watcher loop: device-timestamp and close each handoff.

        ``block_until_ready`` is best-effort — a buffer donated away by a
        later launch before we observe it still yields a device stamp (the
        wait raises, the clock reading stands)."""
        while True:
            item = self._work.get()
            if item is None:
                return
            flows, leaves = item
            try:
                import jax

                jax.block_until_ready(leaves)
            except Exception:  # noqa: BLE001 — see docstring
                pass
            now = _flight._now_us()
            with self._lock:
                for fl in flows:
                    fl.t_device = now
                    self._close_locked(fl)
                self._in_flight -= 1
                self._idle.notify_all()

    def wait_idle(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until every dispatched flow has been closed by the watcher."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._idle:
            while self._in_flight > 0:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._idle.wait(remaining)
        return True

    # ------------------------------------------------------------- closing

    def close_degraded(self, fl: _Flow) -> None:
        """Close a flow whose tick degraded to the synchronous path."""
        fl.degraded = True
        with self._lock:
            self._close_locked(fl)

    def close_dropped(self, fl: _Flow) -> None:
        """Close a flow evicted by backpressure (or a drain=False close)."""
        fl.dropped = True
        with self._lock:
            self._close_locked(fl)

    def close_now(self, flows: Sequence[_Flow]) -> None:
        """Close flows that finished without a chained launch (eager tick)."""
        with self._lock:
            for fl in flows:
                self._close_locked(fl)

    @locked_by("FlowTracer._lock")
    def _close_locked(self, fl: _Flow) -> None:
        """Idempotent close: rollups, flight event, retention (lock held)."""
        if fl.closed:
            return
        fl.closed = True
        self._open.pop(fl.trace_id, None)
        self._closed.append(fl)
        del self._closed[: -self.capacity]
        if fl.dropped:
            self.counts["dropped"] += 1
        else:
            self.counts["completed"] += 1
            if fl.degraded:
                self.counts["degraded"] += 1
            self._pending_readback.setdefault(fl.queue, []).append(fl)
            del self._pending_readback[fl.queue][: -self.capacity]
            ids = self._since_ckpt.setdefault(fl.target_id, [])
            ids.append(fl.trace_id)
            del ids[: -self.capacity]
        breakdown = fl.breakdown_us()
        total_us = max(fl.end_us() - fl.t_enq, 0.0)
        mon = _health._MONITOR
        if mon is not None and not fl.dropped:
            mon.observe_latency("flow", fl.queue, total_us / 1e6)
            for sid in fl.streams:
                mon.observe_latency("flow", f"{fl.queue}/{sid}", total_us / 1e6)
            for stage in ("queue_wait", "coalesce", "compile", "launch", "device"):
                mon.observe_latency("flow_stage", stage, breakdown[stage] / 1e6)
        if _flight._RING is not None:
            _flight.record(
                "flow_complete",
                flow_id=fl.trace_id, id=fl.seq, queue=fl.queue, tick=fl.tick,
                rows=fl.rows, streams=list(fl.streams),
                degraded=fl.degraded, dropped=fl.dropped,
                t_enq_us=fl.t_enq, t_drain_us=fl.t_drain,
                t_launch_us=fl.t_launch, t_dispatch_us=fl.t_dispatch,
                t_device_us=fl.t_device, total_us=round(total_us, 3),
                **{f"{k}_us": round(v, 3) for k, v in breakdown.items()},
            )

    # ------------------------------------------------------------ readback

    def note_readback(self, queue: str, seconds: float) -> None:
        """Stamp a ``compute()`` host-transfer onto the flows it served —
        every completed-but-unread flow of ``queue`` — and feed the stage
        sketch. Called by ``IngestQueue.compute`` with the tracer active."""
        dur_us = seconds * 1e6
        with self._lock:
            served = self._pending_readback.pop(queue, [])
            for fl in served:
                fl.readback_us = dur_us
        mon = _health._MONITOR
        if mon is not None:
            mon.observe_latency("flow_stage", "readback", seconds)
        if _flight._RING is not None and served:
            _flight.record(
                "flow_readback", queue=queue, flows=len(served),
                readback_us=round(dur_us, 3),
            )

    # ---------------------------------------------------------------- ckpt

    def drain_for_ckpt(self, obj: Any) -> List[str]:
        """Flow IDs completed against ``obj`` since the last checkpoint drain
        — the committed checkpoint's flight dump names the flows it contains."""
        with self._lock:
            return self._since_ckpt.pop(id(obj), [])

    # ------------------------------------------------------------- reading

    def records(self) -> List[_Flow]:
        with self._lock:
            return list(self._closed)

    def open_flows(self) -> List[_Flow]:
        with self._lock:
            return list(self._open.values())

    def stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counts)
            out["open"] = len(self._open)
            out["sample_rate"] = self.sample_rate
        return out

    def shutdown(self) -> None:
        self._work.put(None)
        self._watcher.join(timeout=10.0)


# --------------------------------------------------------------- module API


def enable(sample_rate: int = 1, capacity: int = 1024,
           enable_obs: bool = True) -> FlowTracer:
    """Allocate the tracer and start tracing (idempotent: replaces any
    previous tracer). Flow call sites only run inside obs-gated blocks, so by
    default this flips the obs gate on, and — flow latencies feed the health
    sketches — allocates the health monitor if none is active."""
    global _TRACER
    prev = _TRACER
    if prev is not None:
        prev.shutdown()
    if enable_obs:
        _reg.enable()
        if _health._MONITOR is None:
            _health.enable()
    _TRACER = FlowTracer(sample_rate=sample_rate, capacity=capacity)
    return _TRACER


def disable() -> None:
    """Stop tracing and free the tracer (the zero-overhead default)."""
    global _TRACER
    trc = _TRACER
    _TRACER = None
    if trc is not None:
        trc.shutdown()


def active() -> bool:
    return _TRACER is not None


def tracer() -> Optional[FlowTracer]:
    return _TRACER


def stats() -> Dict[str, int]:
    trc = _TRACER
    return trc.stats() if trc is not None else {}


def records() -> List[_Flow]:
    trc = _TRACER
    return trc.records() if trc is not None else []


def wait_idle(timeout: Optional[float] = 10.0) -> bool:
    trc = _TRACER
    return trc.wait_idle(timeout) if trc is not None else True


def drain_for_ckpt(obj: Any) -> List[str]:
    trc = _TRACER
    return trc.drain_for_ckpt(obj) if trc is not None else []


# ------------------------------------------------------------- span export


def _nanos(trc: FlowTracer, ts_us: float) -> int:
    wall, anchor_us = trc.anchor
    return int((wall + (ts_us - anchor_us) / 1e6) * 1e9)


def _span(trace_id: str, span_id: str, parent: str, name: str,
          start_ns: int, end_ns: int, attrs: Dict[str, Any],
          links: Optional[List[Dict[str, str]]] = None) -> Dict[str, Any]:
    return {
        "trace_id": trace_id,
        "span_id": span_id,
        "parent_span_id": parent,
        "name": name,
        "start_time_unix_nano": start_ns,
        "end_time_unix_nano": max(end_ns, start_ns),
        "attributes": attrs,
        "links": links or [],
    }


def flow_spans(flows: Optional[List[_Flow]] = None) -> List[Dict[str, Any]]:
    """OTLP-shaped spans for the given (default: all retained) closed flows.

    One trace per flow: a root ``flow`` span plus one child span per non-zero
    stage. Each coalesced launch additionally yields one ``tick`` root span
    carrying a span **link** per contained flow — the fan-in edge, modeled as
    links because one launch has many causal parents.
    """
    trc = _TRACER
    if trc is None:
        return []
    if flows is None:
        flows = trc.records()
    spans: List[Dict[str, Any]] = []
    ticks: Dict[Tuple[str, int], List[_Flow]] = {}
    for fl in flows:
        root_id = fl.trace_id[:16]
        breakdown = fl.breakdown_us()
        start = _nanos(trc, fl.t_enq)
        end = _nanos(trc, fl.end_us() + fl.readback_us)
        attrs: Dict[str, Any] = {
            "flow.id": fl.trace_id, "flow.queue": fl.queue,
            "flow.rows": fl.rows, "flow.streams": list(fl.streams),
            "degraded": fl.degraded, "dropped": fl.dropped,
            "flow.sync": fl.sync,
        }
        if fl.tick is not None:
            attrs["flow.tick"] = fl.tick
        attrs.update({f"flow.{k}_us": round(v, 3) for k, v in breakdown.items()})
        spans.append(_span(fl.trace_id, root_id, "", "flow", start, end, attrs))
        cursor = fl.t_enq
        for i, stage in enumerate(STAGES):
            dur = breakdown[stage]
            if dur <= 0.0:
                continue
            child_id = f"{int(root_id, 16) ^ (i + 1):016x}"
            spans.append(_span(
                fl.trace_id, child_id, root_id, f"flow/{stage}",
                _nanos(trc, cursor), _nanos(trc, cursor + dur),
                {"flow.stage": stage, "flow.queue": fl.queue},
            ))
            cursor += dur
        if fl.tick is not None:
            ticks.setdefault((fl.queue, fl.tick), []).append(fl)
    for (queue, tick), members in sorted(ticks.items()):
        digest = hashlib.sha256(f"tick/{queue}/{tick}".encode()).hexdigest()
        t0 = min(m.t_launch or m.t_enq for m in members)
        t1 = max(m.t_device or m.end_us() for m in members)
        spans.append(_span(
            digest[:32], digest[32:48], "", "tick",
            _nanos(trc, t0), _nanos(trc, t1),
            {"flow.queue": queue, "flow.tick": tick, "flow.fan_in": len(members)},
            links=[{"trace_id": m.trace_id, "span_id": m.trace_id[:16]}
                   for m in members],
        ))
    return spans


def export_spans(path: Optional[str] = None,
                 flows: Optional[List[_Flow]] = None) -> List[Dict[str, Any]]:
    """Write the span set as JSONL (one span per line); returns the spans."""
    import json

    spans = flow_spans(flows)
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            for span in spans:
                fh.write(json.dumps(span, sort_keys=True) + "\n")
    return spans


_HEX = frozenset("0123456789abcdef")


def _is_hex(value: Any, width: int) -> bool:
    return (
        isinstance(value, str) and len(value) == width and set(value) <= _HEX
    )


def validate_spans(spans: List[Dict[str, Any]]) -> int:
    """Structurally validate an exported span set; returns the span count.

    The dependency-free analogue of ``prom.validate_exposition`` /
    ``trace.validate_chrome_trace`` for the span path: OTLP-shaped id widths
    (32-hex trace, 16-hex span), unique ``(trace_id, span_id)``, parent and
    link references that resolve *within the set*, and ``start <= end``.
    Raises ``ValueError`` naming the first offending span.
    """
    if not isinstance(spans, list):
        raise ValueError("span export must be a list of span objects")
    seen: set = set()
    for i, sp in enumerate(spans):
        if not isinstance(sp, dict):
            raise ValueError(f"spans[{i}] is not an object")
        if not _is_hex(sp.get("trace_id"), 32):
            raise ValueError(f"spans[{i}] trace_id must be 32 lowercase hex chars")
        if not _is_hex(sp.get("span_id"), 16):
            raise ValueError(f"spans[{i}] span_id must be 16 lowercase hex chars")
        key = (sp["trace_id"], sp["span_id"])
        if key in seen:
            raise ValueError(f"spans[{i}] duplicates span {key}")
        seen.add(key)
        parent = sp.get("parent_span_id")
        if not (parent == "" or _is_hex(parent, 16)):
            raise ValueError(f"spans[{i}] parent_span_id must be '' or 16-hex")
        if not isinstance(sp.get("name"), str) or not sp["name"]:
            raise ValueError(f"spans[{i}] missing non-empty string name")
        start, end = sp.get("start_time_unix_nano"), sp.get("end_time_unix_nano")
        if not isinstance(start, int) or not isinstance(end, int) or end < start:
            raise ValueError(f"spans[{i}] needs integer start <= end nanos")
        if not isinstance(sp.get("attributes"), dict):
            raise ValueError(f"spans[{i}] attributes must be an object")
        if not isinstance(sp.get("links"), list):
            raise ValueError(f"spans[{i}] links must be a list")
    for i, sp in enumerate(spans):
        parent = sp.get("parent_span_id")
        if parent and (sp["trace_id"], parent) not in seen:
            raise ValueError(
                f"spans[{i}] parent {parent!r} does not resolve within the set"
            )
        for j, link in enumerate(sp["links"]):
            if not isinstance(link, dict) or (
                link.get("trace_id"), link.get("span_id")
            ) not in seen:
                raise ValueError(
                    f"spans[{i}] link[{j}] does not resolve within the set"
                )
    return len(spans)
