"""Named trace scopes: make XProf/Perfetto traces attribute HLO to metrics.

Every scope is the pair ``jax.named_scope`` (names the ops in the jaxpr/HLO, so
the XLA op-profile groups by metric) + ``jax.profiler.TraceAnnotation`` (marks
the host thread's dispatch window, so the trace timeline shows which metric
issued which device work). Naming convention:

    tm.update/<MetricClassName>     one metric update
    tm.compute/<MetricClassName>    one metric compute
    tm.forward/<MetricClassName>    dual-purpose forward
    tm.collection.update            MetricCollection fan-out
    tm.sync/<reduce_fx>             one collective state sync
    tm.rank/<tier>                  one rank-engine dispatch (ops/rank.py)

Callers in the hot path gate on ``registry._ENABLED`` *before* building the
context manager, so the disabled path never allocates one. ``trace(path)`` is
the one-call capture driver around ``jax.profiler``.
"""
import time
from contextlib import contextmanager
from typing import Iterator, Optional

import jax

from metrics_tpu.obs import flight as _flight
from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _reg


@contextmanager
def annotate(label: str) -> Iterator[None]:
    """Enter ``jax.named_scope(label)`` + ``jax.profiler.TraceAnnotation(label)``.

    Also counts the entry under ``("scopes", label)`` so tests (and exported
    snapshots) can assert which annotations a run emitted without parsing a
    binary trace. When the flight recorder or the health monitor is active the
    window is additionally *timed* (two ``perf_counter`` reads) — the flight
    ring gets a ``scope`` event the Perfetto exporter renders as a slice, and
    the health sketches get the latency sample. Counting-only mode stays
    timer-free.
    """
    _reg.REGISTRY.inc("scopes", label)
    timed = _flight._RING is not None or _health._MONITOR is not None
    t0 = time.perf_counter() if timed else 0.0
    with jax.named_scope(label), jax.profiler.TraceAnnotation(label):
        yield
    if timed:
        dt = time.perf_counter() - t0
        if _flight._RING is not None:
            _flight.record("scope", ts_us=t0 * 1e6, name=label, dur_us=dt * 1e6)
        monitor = _health._MONITOR
        if monitor is not None:
            monitor.observe_scope(label, dt)


def update_scope(metric_name: str):
    return annotate(f"tm.update/{metric_name}")


def compute_scope(metric_name: str):
    return annotate(f"tm.compute/{metric_name}")


def forward_scope(metric_name: str):
    return annotate(f"tm.forward/{metric_name}")


def sync_scope(reduce_fx) -> "annotate":
    kind = reduce_fx if isinstance(reduce_fx, str) else (
        "stack" if reduce_fx is None else getattr(reduce_fx, "__name__", "custom")
    )
    return annotate(f"tm.sync/{kind}")


@contextmanager
def trace(path: str, create_perfetto_link: bool = False, enable_obs: bool = True) -> Iterator[str]:
    """One-call profile capture: ``with obs.trace("/tmp/prof"): eval_step()``.

    Drives ``jax.profiler.start_trace``/``stop_trace`` and (by default) enables
    the instrumentation layer for the duration so the captured trace carries the
    ``tm.*`` annotations.
    """
    prev = _reg.enabled()
    if enable_obs:
        _reg.enable()
    jax.profiler.start_trace(path, create_perfetto_link=create_perfetto_link)
    try:
        yield path
    finally:
        jax.profiler.stop_trace()
        if enable_obs and not prev:
            _reg.disable()
