"""Runtime↔static cost crosscheck: do tmsan's predictions hold on the hot path?

The analysis tier (``metrics_tpu.analysis.san``) checks a compile-cost budget
into ``tmsan_costs.json``: for every metric entry at its canonical shape, ONE
executable per ``update`` call, with XLA-modelled flops/bytes. That is a
*promise about the runtime* made without running anything — this module closes
the loop by comparing it against what the obs registry actually measured.

The observable the two tiers share is the **launch count per update**: the
static model says a budgeted ``<Class>.update`` costs one dispatch per call
(``dispatches / updates == 1``). A measured ratio above ``1 + tolerance``
means the runtime is launching more executables per update than the analysis
tier certified — un-jitted glue, a shape-polymorphic path fanning out, or an
instrumentation bug — and surfaces as a :class:`CostDriftWarning` plus a
structured report entry (also embedded in ``bench.py --obs-trace`` output).
A ratio *below* ``1 - tolerance`` is the good kind of drift (fused/batched
updates amortizing launches) and is reported as a note, never a warning.

Version skew follows the same policy as ``analysis/san/costs.py``: the budget
file stamps the jax version/backend it was recorded on; on a mismatch the
comparison still runs but drift degrades to notes — cross-version behaviour is
XLA's business, same-version drift is this repo's regression.

Zero-overhead contract: this module measures nothing itself — it only *reads*
the registry snapshot (and per-scope wall timers when the scope timing added
by flight/health was active), so with the gate off there is nothing to check
and :func:`crosscheck` returns an empty report.
"""
import os
import warnings
from typing import Any, Dict, List, Optional

from metrics_tpu.obs import registry as _reg

#: launch-count drift beyond this fraction of the static model is a warning
#: (mirrors ``analysis.san.costs.BUDGET_TOLERANCE``)
DRIFT_TOLERANCE = 0.15

#: registry scopes that are infrastructure, not budgeted metric classes
_INFRA_SCOPES = frozenset(
    {"fused", "fleet", "scopes", "bench", "jax", "sync", "ckpt", "collection", "health"}
)

#: the static model's launches-per-update for a budgeted entry
_STATIC_LAUNCHES_PER_UPDATE = 1.0


class CostDriftWarning(RuntimeWarning):
    """Measured launch counts drifted past tolerance from tmsan's static model."""


def default_costs_path() -> Optional[str]:
    """``tmsan_costs.json`` at the repo root (the package's parent dir)."""
    import metrics_tpu

    root = os.path.dirname(os.path.dirname(os.path.abspath(metrics_tpu.__file__)))
    cand = os.path.join(root, "tmsan_costs.json")
    return cand if os.path.exists(cand) else None


def budgeted_classes(payload: Dict[str, Any]) -> Dict[str, int]:
    """Metric class names with at least one ``<Class>.update[...]`` budget entry,
    mapped to how many shape variants the budget records for them."""
    out: Dict[str, int] = {}
    for key in payload.get("entries", {}):
        head, _, _ = key.partition("[")
        cls, dot, op = head.partition(".")
        if dot and op == "update" and cls and cls[0].isupper():
            out[cls] = out.get(cls, 0) + 1
    return out


def _scope_wall_s(counters: Dict[str, Any]) -> Optional[float]:
    """Sum the wall-time timers recorded for a scope (None when none exist)."""
    total = None
    for value in counters.values():
        if isinstance(value, dict) and "total_s" in value:
            total = (total or 0.0) + float(value["total_s"])
    return total


def crosscheck(
    costs_path: Optional[str] = None,
    tolerance: float = DRIFT_TOLERANCE,
    snapshot: Optional[Dict[str, Dict[str, Any]]] = None,
    warn: bool = True,
) -> Dict[str, Any]:
    """Compare measured launch counts against the static budget; return a report.

    Report layout::

        {"costs_path", "tolerance", "static_jax", "version_ok",
         "checked":   [{scope, updates, dispatches, launches_per_update,
                        wall_s?}, ...],
         "drifts":    [same rows, measured > 1 + tolerance],
         "amortized": [same rows, measured < 1 - tolerance],
         "unbudgeted": [scopes measured but absent from the budget],
         "notes": [...]}

    ``warn=True`` raises one :class:`CostDriftWarning` naming every drifted
    scope (suppressed to a note on jax version/backend skew).
    """
    report: Dict[str, Any] = {
        "costs_path": None,
        "tolerance": tolerance,
        "static_jax": None,
        "version_ok": None,
        "checked": [],
        "drifts": [],
        "amortized": [],
        "unbudgeted": [],
        "notes": [],
    }
    path = costs_path or default_costs_path()
    if path is None or not os.path.exists(path):
        report["notes"].append(
            "tmsan_costs.json not found: run `python -m metrics_tpu.analysis --san"
            " --write-costs` to record the static budget"
        )
        return report
    from metrics_tpu.analysis.san.costs import load_costs

    try:
        payload = load_costs(path)
    except Exception as exc:  # noqa: BLE001 — a broken budget file is a note, not a crash
        report["notes"].append(f"failed to load {path}: {exc}")
        return report
    report["costs_path"] = path
    report["static_jax"] = f"{payload.get('jax')}/{payload.get('backend')}"

    import jax

    version_ok = payload.get("jax") == jax.__version__ and (
        payload.get("backend") == jax.default_backend()
    )
    report["version_ok"] = bool(version_ok)
    if not version_ok:
        report["notes"].append(
            f"budget recorded on jax={payload.get('jax')}/{payload.get('backend')}"
            f" but running jax={jax.__version__}/{jax.default_backend()}:"
            " drift reported as notes, not warnings"
        )

    budget = budgeted_classes(payload)
    snap = snapshot if snapshot is not None else _reg.snapshot()

    for scope in sorted(snap):
        if scope in _INFRA_SCOPES:
            continue
        counters = snap[scope]
        updates = counters.get("updates")
        if not isinstance(updates, (int, float)) or updates <= 0:
            continue
        dispatches = counters.get("dispatches", 0)
        if not isinstance(dispatches, (int, float)):
            continue
        if scope not in budget:
            report["unbudgeted"].append(scope)
            continue
        row: Dict[str, Any] = {
            "scope": scope,
            "updates": int(updates),
            "dispatches": int(dispatches),
            "launches_per_update": round(dispatches / updates, 4),
            "static_launches_per_update": _STATIC_LAUNCHES_PER_UPDATE,
            "budget_variants": budget[scope],
        }
        wall = _scope_wall_s(counters)
        if wall is not None:
            row["wall_s"] = round(wall, 6)
        ratio = dispatches / updates
        if ratio > _STATIC_LAUNCHES_PER_UPDATE * (1.0 + tolerance):
            report["drifts"].append(row)
        elif ratio < _STATIC_LAUNCHES_PER_UPDATE * (1.0 - tolerance):
            report["amortized"].append(row)
        else:
            report["checked"].append(row)

    if report["drifts"]:
        msg = "; ".join(
            f"{r['scope']}: {r['launches_per_update']:.2f} launches/update"
            f" vs static {_STATIC_LAUNCHES_PER_UPDATE:.2f}"
            f" (+{(r['launches_per_update'] / _STATIC_LAUNCHES_PER_UPDATE - 1) * 100:.0f}%)"
            for r in report["drifts"]
        )
        text = (
            f"runtime launch counts drifted past the +{tolerance * 100:.0f}% static"
            f" budget from {os.path.basename(path)} — {msg}. The serving path is"
            " launching more executables per update than tmsan certified; fix the"
            " dispatch regression or refresh the budget with an explanation."
        )
        if warn and version_ok:
            warnings.warn(text, CostDriftWarning, stacklevel=2)
        else:
            report["notes"].append(text)
    return report
