"""Flight recorder: a bounded ring of structured step events, dumped on failure.

A metrics stack that dies on a preempted TPU slice leaves nothing behind but an
exit code; the questions that matter — *what was the last fused launch? did the
checkpoint commit? was the job mid-retrace-storm?* — need the last few hundred
runtime events, not a profiler session that was never started. The flight
recorder keeps exactly that: a fixed-capacity ring (:class:`obs.ring.Ring`) of
small structured events appended by the instrumented hot paths, and a
``dump()`` that writes the surviving window (plus ``state_report()`` snapshots
of recently-checkpointed metrics) as one JSON file.

Event kinds emitted by the runtime (all behind the obs gate):

    ``dispatch``          one eager metric update dispatch (metric, input avals)
    ``scope``             one timed ``tm.*`` scope (name, ts_us, dur_us)
    ``retrace``           a metric accumulated a new update signature
    ``fused_launch``      one fused-collection XLA launch (groups, cache key)
    ``fused_cache_miss``  the fused engine compiled a new executable
    ``fleet_route``       one routed fleet batch (rows, streams)
    ``merge``             one ``merge_state`` (sketch merges ride this hook)
    ``excache_prewarm``   one warm-manifest replay (entries/compiled/seconds)
    ``ckpt_save_begin`` / ``ckpt_save_commit`` / ``ckpt_restore``
    ``flow_begin`` / ``flow_complete`` / ``flow_dropped`` / ``flow_readback``
                          tmflow request-tracing lifecycle (obs/flow.py)
    ``ckpt_flows``        flow IDs contained in a checkpoint being saved

Correlation: events on a traced request path carry an optional ``flow_id``
field (the tmflow trace id, ``obs/flow.py``); pre-flow events simply omit it
— schema_version 2 of the dump admits both forms.

Gating contract (the single-boolean rule of ``registry.py``): every call site
lives inside an existing ``if registry._ENABLED:`` block and additionally
checks ``flight._RING is not None`` before touching this module — with obs off
the recorder costs nothing, and **no ring storage exists until**
:func:`enable` allocates it (disabled-mode no-allocation guarantee, verified
by ``tests/unittests/obs/test_tmprof.py``).

Dump-on-failure: :func:`enable` can install an ``atexit`` hook and chain-
preserving ``signal`` handlers (SIGTERM by default — the preemption notice) so
the last-K events survive a kill at any point, including between an update and
its checkpoint commit. The opt-in ``ckpt_integration`` additionally writes the
dump *into* each checkpoint's tmp dir before the atomic commit, so every
committed step carries the flight window that produced it.
"""
import atexit
import itertools
import json
import os
import signal as _signal
import sys
import threading
import time
import weakref
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs.ring import Ring

#: schema stamp of the dump file (bump on breaking layout changes).
#: History: 1 = original layout; 2 = flow-era dumps (``flow_*``/``ckpt_flows``
#: event kinds, optional ``flow_id`` correlation field on request-path
#: events). Readers accept both — v2 only *adds* fields and kinds.
DUMP_SCHEMA_VERSION = 2

#: the ring itself. ``None`` == recorder off == nothing allocated; hot paths
#: gate on ``_RING is not None`` (one module-attribute load + identity check).
_RING: Optional[Ring] = None

_SEQ = itertools.count()
_LOCK = threading.Lock()

#: configuration captured by :func:`enable`
_DUMP_PATH: Optional[str] = None
_CKPT_INTEGRATION: bool = False
_CAPACITY: int = 0

#: weakrefs to objects whose ``state_report()``/``summary()`` rides every dump
#: (registered by ``ckpt.save_checkpoint`` — the post-mortem wants the state
#: layout of whatever was being checkpointed)
_STATE_SOURCES: "List[weakref.ref]" = []

#: previously-installed signal handlers, for chaining + uninstall
_PREV_HANDLERS: Dict[int, Any] = {}
_ATEXIT_REGISTERED = False

#: the sys.excepthook in place before ours, for chaining + uninstall
#: (sentinel None == not installed)
_PREV_EXCEPTHOOK: Optional[Any] = None


def _now_us() -> float:
    """Monotonic microsecond timebase shared with the trace exporter."""
    return time.perf_counter() * 1e6


def enable(
    capacity: int = 512,
    dump_path: Optional[str] = None,
    install_handlers: bool = False,
    signals: Tuple[int, ...] = (_signal.SIGTERM,),
    ckpt_integration: bool = False,
    enable_obs: bool = True,
) -> None:
    """Allocate the ring and start recording.

    Args:
        capacity: events retained (the "last K" of every dump).
        dump_path: where crash dumps go; required for ``install_handlers``.
        install_handlers: register an ``atexit`` hook, chaining handlers on
            ``signals``, and a chaining ``sys.excepthook`` — each writes
            ``dump_path`` before the process dies, covering preemption,
            clean exit, and an uncaught exception alike. Handlers forward to
            whatever was installed before them (or re-deliver the signal with
            the default action, so the exit status stays honest).
        signals: which signals to hook (default SIGTERM, the preemption
            notice; add SIGINT for interactive runs).
        ckpt_integration: opt-in — every ``ckpt.save_checkpoint`` also writes
            the current window as ``flight-h<rank>.json`` inside the step dir,
            committed atomically with the checkpoint itself.
        enable_obs: flight events are only emitted from obs-gated call sites,
            so by default this flips the obs gate on too.
    """
    global _RING, _DUMP_PATH, _CKPT_INTEGRATION, _CAPACITY
    if capacity < 1:
        raise ValueError(f"flight recorder capacity must be >= 1, got {capacity}")
    with _LOCK:
        _RING = Ring(capacity)
        _CAPACITY = capacity
        _DUMP_PATH = dump_path
        _CKPT_INTEGRATION = bool(ckpt_integration)
    if enable_obs:
        from metrics_tpu.obs import registry as _reg

        _reg.enable()
    if install_handlers:
        if dump_path is None:
            raise ValueError("install_handlers=True requires dump_path")
        _install_handlers(signals)


def disable() -> None:
    """Stop recording and free the ring; uninstalls any crash handlers."""
    global _RING, _DUMP_PATH, _CKPT_INTEGRATION
    _uninstall_handlers()
    with _LOCK:
        _RING = None
        _DUMP_PATH = None
        _CKPT_INTEGRATION = False
        _STATE_SOURCES.clear()


def active() -> bool:
    return _RING is not None


def ckpt_integration_active() -> bool:
    return _RING is not None and _CKPT_INTEGRATION


def capacity() -> int:
    return _CAPACITY if _RING is not None else 0


# -------------------------------------------------------------- recording


def record(kind: str, ts_us: Optional[float] = None, **fields: Any) -> None:
    """Append one event; no-op when the recorder is off.

    Events are plain dicts ``{seq, ts_us, kind, **fields}`` — ``seq`` is a
    process-global monotone counter so dumps are orderable even if two threads
    land the same microsecond; ``ts_us`` is the ``perf_counter`` microsecond
    timebase the Perfetto exporter (``obs/trace.py``) uses directly.
    """
    ring = _RING
    if ring is None:
        return
    event = {"seq": next(_SEQ), "ts_us": _now_us() if ts_us is None else ts_us, "kind": kind}
    event.update(fields)
    ring.append(event)  # Ring.append is GIL-atomic and lock-free (obs/ring.py)


def _aval_str(x: Any) -> str:
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return "x".join(map(str, shape)) + f":{dtype}"
    if isinstance(x, (bool, int, float)):
        return f"py:{type(x).__name__}={x}"
    return type(x).__name__


def record_dispatch(
    metric_name: str, args: Tuple, kwargs: Dict, flow_id: Optional[str] = None
) -> None:
    """One eager update dispatch, args summarized as avals (never values).

    ``flow_id`` is the optional tmflow correlation id (``obs/flow.py``);
    ``None`` — every pre-flow caller — keeps the event byte-identical to
    schema_version 1 dumps.
    """
    if _RING is None:
        return
    avals = [_aval_str(a) for a in args]
    avals += [f"{k}={_aval_str(v)}" for k, v in kwargs.items()]
    if flow_id is None:
        record("dispatch", metric=metric_name, avals=avals)
    else:
        record("dispatch", metric=metric_name, avals=avals, flow_id=flow_id)


def events() -> List[Dict[str, Any]]:
    """Snapshot of the current window, oldest first.

    ``Ring.snapshot`` retries the rare iterate-during-append ``RuntimeError``
    rather than locking the hot-path append.
    """
    ring = _RING
    if ring is None:
        return []
    return ring.snapshot()


def last(k: int) -> List[Dict[str, Any]]:
    return events()[-k:]


def clear() -> None:
    ring = _RING
    if ring is not None:
        ring.clear()


# ------------------------------------------------------- state-report riders


def note_state_source(obj: Any) -> None:
    """Remember ``obj`` (weakly) so its state report rides future dumps."""
    if _RING is None:
        return
    with _LOCK:
        refs = [r for r in _STATE_SOURCES if r() is not None and r() is not obj]
        refs.append(weakref.ref(obj))
        del _STATE_SOURCES[:]
        _STATE_SOURCES.extend(refs[-8:])  # the post-mortem needs recent, not all


def _state_reports() -> List[Dict[str, Any]]:
    """Resolve the registered state sources without ever blocking.

    This runs inside the atexit/signal/excepthook dump path, which can preempt
    a thread that is *currently inside* :func:`note_state_source` holding
    ``_LOCK`` — a blocking acquire here would deadlock the post-mortem at the
    exact moment it matters. Try-lock; on contention fall back to a lock-free
    ``list()`` snapshot (``_STATE_SOURCES`` only ever holds weakrefs, and a
    torn read costs at most one stale/missing rider, never a crash).
    """
    out = []
    if _LOCK.acquire(blocking=False):
        try:
            objs = [r() for r in _STATE_SOURCES]
        finally:
            _LOCK.release()
    else:
        objs = [r() for r in list(_STATE_SOURCES)]
    for obj in objs:
        if obj is None:
            continue
        try:
            if hasattr(obj, "state_report"):
                out.append(obj.state_report())
            elif hasattr(obj, "summary"):
                out.append(obj.summary())
        except Exception:  # noqa: BLE001 — a post-mortem must never throw
            continue
    return out


# --------------------------------------------------------------------- dump


def dump(path: Optional[str] = None, state_objs: Optional[List[Any]] = None) -> Optional[str]:
    """Write the surviving window as one JSON file; returns the path.

    The dump is self-contained: schema stamp, wall-clock anchor (so ``ts_us``
    offsets translate to absolute time), capacity, the events oldest-first,
    and ``state_report()`` snapshots of the registered state sources (plus any
    ``state_objs`` passed explicitly — the ckpt integration passes the object
    being saved). Best-effort by design: returns ``None`` instead of raising
    when the recorder is off or the write fails mid-crash.
    """
    path = path or _DUMP_PATH
    ring = _RING
    if ring is None or path is None:
        return None
    reports = _state_reports()
    for obj in state_objs or ():
        try:
            if hasattr(obj, "state_report"):
                reports.append(obj.state_report())
            elif hasattr(obj, "summary"):
                reports.append(obj.summary())
        except Exception:  # noqa: BLE001
            pass
    payload = {
        "schema_version": DUMP_SCHEMA_VERSION,
        "dumped_unix": time.time(),
        "anchor_us": _now_us(),
        "capacity": _CAPACITY,
        "events": events(),
        "state_reports": reports,
    }
    try:
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(payload, fh, default=str)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path
    except Exception:  # noqa: BLE001 — a failing dump must not mask the crash
        return None


# -------------------------------------------------------- failure handlers


def failure_dump_path() -> Optional[str]:
    """Where the atexit/signal handlers will write: ``dump_path`` suffixed
    with process rank + pid (``…-h0000-p12345.json``).

    Concurrent multi-process dumps into one shared directory must not
    overwrite each other; the rank matches the ckpt-embedded
    ``flight-h<rank>.json`` naming, and the pid disambiguates external
    launchers that map several processes to one rank. Explicit
    :func:`dump` calls keep the caller's path verbatim.
    """
    if _DUMP_PATH is None:
        return None
    try:
        from metrics_tpu.parallel.collective import process_topology

        rank, _ = process_topology()
    except Exception:  # noqa: BLE001 — mid-crash, a best-effort name beats none
        rank = 0
    root, ext = os.path.splitext(_DUMP_PATH)
    return f"{root}-h{rank:04d}-p{os.getpid()}{ext or '.json'}"


def _on_exit() -> None:
    if _RING is not None and _DUMP_PATH is not None:
        dump(failure_dump_path())


def _on_signal(signum: int, frame: Any) -> None:
    dump(failure_dump_path())
    prev = _PREV_HANDLERS.get(signum)
    if callable(prev):
        prev(signum, frame)
        return
    # previous handler was the default (or SIG_IGN): restore it and re-deliver
    # so the process dies with the honest signal exit status
    _signal.signal(signum, prev if prev is not None else _signal.SIG_DFL)
    os.kill(os.getpid(), signum)


def _on_unhandled(exc_type: Any, exc: Any, tb: Any) -> None:
    """Chaining ``sys.excepthook``: an uncaught exception is a crash that is
    neither a signal nor a clean exit — record it, dump the window to the same
    rank+pid-disambiguated path the other failure handlers use, then hand the
    exception to whatever hook was installed before us (the interpreter's
    default printer, unless someone else chained first)."""
    try:
        if _RING is not None:
            record(
                "unhandled_exception",
                exc_type=getattr(exc_type, "__name__", str(exc_type)),
                message=str(exc)[:200],
            )
            dump(failure_dump_path())
    except Exception:  # noqa: BLE001 — the hook must never mask the crash
        pass
    prev = _PREV_EXCEPTHOOK
    (prev if callable(prev) else sys.__excepthook__)(exc_type, exc, tb)


def _install_handlers(signals: Tuple[int, ...]) -> None:
    global _ATEXIT_REGISTERED, _PREV_EXCEPTHOOK
    if not _ATEXIT_REGISTERED:
        atexit.register(_on_exit)
        _ATEXIT_REGISTERED = True
    if _PREV_EXCEPTHOOK is None:
        _PREV_EXCEPTHOOK = sys.excepthook
        sys.excepthook = _on_unhandled
    for signum in signals:
        if signum in _PREV_HANDLERS:
            continue
        try:
            _PREV_HANDLERS[signum] = _signal.signal(signum, _on_signal)
        except (ValueError, OSError):  # non-main thread / unsupported signal
            continue


def _uninstall_handlers() -> None:
    global _PREV_EXCEPTHOOK
    if _PREV_EXCEPTHOOK is not None:
        # only restore if nobody chained on top of us in the meantime
        if sys.excepthook is _on_unhandled:
            sys.excepthook = _PREV_EXCEPTHOOK
        _PREV_EXCEPTHOOK = None
    for signum, prev in list(_PREV_HANDLERS.items()):
        try:
            _signal.signal(signum, prev if prev is not None else _signal.SIG_DFL)
        except (ValueError, OSError):
            pass
        _PREV_HANDLERS.pop(signum, None)
