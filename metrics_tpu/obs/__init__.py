"""metrics_tpu.obs — zero-overhead instrumentation layer.

Quickstart::

    import metrics_tpu.obs as obs

    obs.enable()                      # counters + scopes + retrace detection
    metric.update(preds, target)      # counted, annotated, fingerprinted
    obs.snapshot()                    # {"MulticlassAccuracy": {"updates": 1}, ...}
    metric.state_report()             # per-state dtype/shape/nbytes/sharding/fill

    with obs.trace("/tmp/profile"):   # one-call XProf capture; the trace shows
        eval_step()                   # tm.update/<Metric> and tm.sync/<fx> scopes

Off by default: with obs disabled every instrumented hot path reduces to a
single boolean check (see ``registry.py``), keeping the library's measured
throughput identical to the uninstrumented build.
"""
from metrics_tpu.obs.registry import (
    REGISTRY,
    ObsRegistry,
    disable,
    enable,
    enabled,
    observe,
    snapshot,
    snapshot_json,
)
from metrics_tpu.obs import recompile, registry
from metrics_tpu.obs.export import dump_jsonl
from metrics_tpu.obs.export import snapshot as export_snapshot
from metrics_tpu.obs.recompile import (
    RETRACE_WARN_THRESHOLD,
    fingerprint,
    reset_class_detector,
    reset_detector,
)
from metrics_tpu.obs.report import collection_summary, metric_state_report
from metrics_tpu.obs.scopes import (
    annotate,
    compute_scope,
    forward_scope,
    sync_scope,
    trace,
    update_scope,
)


def stopwatch(scope: str, name: str = "elapsed"):
    """Module-level shortcut for ``REGISTRY.stopwatch`` (used by bench.py)."""
    return REGISTRY.stopwatch(scope, name)


__all__ = [
    "REGISTRY",
    "RETRACE_WARN_THRESHOLD",
    "ObsRegistry",
    "annotate",
    "collection_summary",
    "compute_scope",
    "disable",
    "dump_jsonl",
    "enable",
    "enabled",
    "export_snapshot",
    "fingerprint",
    "forward_scope",
    "metric_state_report",
    "observe",
    "recompile",
    "registry",
    "reset_class_detector",
    "reset_detector",
    "snapshot",
    "snapshot_json",
    "stopwatch",
    "sync_scope",
    "trace",
    "update_scope",
]
