"""metrics_tpu.obs — zero-overhead instrumentation layer.

Quickstart::

    import metrics_tpu.obs as obs

    obs.enable()                      # counters + scopes + retrace detection
    metric.update(preds, target)      # counted, annotated, fingerprinted
    obs.snapshot()                    # {"MulticlassAccuracy": {"updates": 1}, ...}
    metric.state_report()             # per-state dtype/shape/nbytes/sharding/fill

    with obs.trace("/tmp/profile"):   # one-call XProf capture; the trace shows
        eval_step()                   # tm.update/<Metric> and tm.sync/<fx> scopes

tmprof — the production telemetry tier on the same gate::

    obs.flight.enable(dump_path="/tmp/flight.json", install_handlers=True)
    train()                              # ring of dispatches/launches/retraces/...
    obs.export_chrome_trace("/tmp/tm-trace.json")   # load in ui.perfetto.dev

    obs.health.enable()                  # latency sketches + HBM watermark
    obs.health.set_slo(p99_update_latency_ms=5.0)
    obs.health.check_slos()

    obs.costcheck.crosscheck()           # measured launches vs tmsan_costs.json

tmscope — continuous monitoring on the same gate::

    obs.series.enable(interval_s=1.0)    # 1 Hz counter-delta + percentile ring
    obs.prom.start_server(port=9464)     # GET /metrics, Prometheus text format
    obs.aggregate.fleet_snapshot()       # cross-host merge (sketch-exact p99s)

tmflow — causal request tracing on the same gate::

    obs.flow.enable(sample_rate=1)       # flow IDs: enqueue -> tick -> device
    queue.enqueue(preds, target)         # traced end to end, per-tenant
    obs.export_spans("/tmp/spans.jsonl") # OTLP-shaped spans; the chrome-trace
                                         # export grows flow arrows too

Off by default: with obs disabled every instrumented hot path reduces to a
single boolean check (see ``registry.py``), keeping the library's measured
throughput identical to the uninstrumented build — and none of the tmprof
surfaces (flight ring, sketches) allocate anything until their own
``enable()``.
"""
from metrics_tpu.obs.registry import (
    REGISTRY,
    ObsRegistry,
    disable,
    enable,
    enabled,
    observe,
    snapshot,
    snapshot_json,
)
# NOTE import order: the `trace` submodule must bind into the package BEFORE
# the `from ...scopes import trace` below rebinds the package attribute
# `obs.trace` to the XProf capture contextmanager (the documented public name).
# The exporter stays reachable as `obs.export_chrome_trace` / via
# `metrics_tpu.obs import trace as trace_export`.
from metrics_tpu.obs import aggregate, costcheck, flight, flow, health, prom, recompile, registry, ring, series
from metrics_tpu.obs.ring import Ring
from metrics_tpu.obs import trace as _trace_export
from metrics_tpu.obs.costcheck import CostDriftWarning, crosscheck
from metrics_tpu.obs.flow import export_spans, validate_spans
from metrics_tpu.obs.export import SCHEMA_VERSION, dump_jsonl, validate_snapshot
from metrics_tpu.obs.export import snapshot as export_snapshot
from metrics_tpu.obs.health import (
    SLOBudget,
    SLOBudgetExceeded,
    SLOViolationWarning,
)
from metrics_tpu.obs.recompile import (
    RETRACE_WARN_THRESHOLD,
    fingerprint,
    reset_class_detector,
    reset_detector,
)
from metrics_tpu.obs.report import collection_summary, metric_state_report
from metrics_tpu.obs.scopes import (
    annotate,
    compute_scope,
    forward_scope,
    sync_scope,
    trace,
    update_scope,
)
from metrics_tpu.obs.trace import (
    chrome_trace_events,
    export_chrome_trace,
    validate_chrome_trace,
)


def stopwatch(scope: str, name: str = "elapsed"):
    """Module-level shortcut for ``REGISTRY.stopwatch`` (used by bench.py)."""
    return REGISTRY.stopwatch(scope, name)


__all__ = [
    "REGISTRY",
    "RETRACE_WARN_THRESHOLD",
    "SCHEMA_VERSION",
    "CostDriftWarning",
    "ObsRegistry",
    "Ring",
    "SLOBudget",
    "SLOBudgetExceeded",
    "SLOViolationWarning",
    "aggregate",
    "annotate",
    "chrome_trace_events",
    "collection_summary",
    "compute_scope",
    "costcheck",
    "crosscheck",
    "disable",
    "dump_jsonl",
    "enable",
    "enabled",
    "export_chrome_trace",
    "export_snapshot",
    "export_spans",
    "fingerprint",
    "flight",
    "flow",
    "forward_scope",
    "health",
    "metric_state_report",
    "observe",
    "prom",
    "recompile",
    "registry",
    "reset_class_detector",
    "reset_detector",
    "ring",
    "series",
    "snapshot",
    "snapshot_json",
    "stopwatch",
    "sync_scope",
    "trace",
    "update_scope",
    "validate_chrome_trace",
    "validate_snapshot",
    "validate_spans",
]
