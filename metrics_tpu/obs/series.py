"""Continuous telemetry series: a background sampler over the obs registry.

``obs/health`` answers "how slow, at what tail" at the moment someone asks;
a serving deployment needs the same signals as *time series* an external
scraper can collect — launches/step over the last minute, p99 drift across a
deploy, the HBM watermark climbing toward an OOM. This module runs a daemon
thread that, every ``interval_s``, snapshots registry counter **deltas**
(what happened since the previous tick, not since process start) plus the
health monitor's percentile flushes into a fixed-capacity ring of ticks, and
evaluates :func:`metrics_tpu.obs.health.check_slos` per tick — SLOs become
continuous instead of call-site-driven.

Tick layout (one dict per tick, oldest first in :func:`ticks`)::

    {"t_unix": ..., "dt_s": ...,            # wall anchor + actual tick width
     "counters": {scope: {name: delta}},    # numeric counter deltas
     "timers":   {scope: {name: {count, total_s}}},  # timer deltas
     "latency_us": {...},                   # health.report()["latency_us"]
     "hbm_watermark_bytes": ...,            # health watermark (None w/o monitor)
     "slo_violations": [...]}               # check_slos() result this tick

Zero-overhead contract (the ``health.py`` discipline): module global
``_SAMPLER`` stays ``None`` until :func:`enable` — no ring, no thread, no
state before then — and the instrumented hot paths never call into this
module at all (the sampler *pulls* from the registry; nothing pushes). The
sampler's own work runs entirely off the hot path: ``registry.snapshot()``
takes the registry lock briefly, and the health flush/percentile computes
already suppress the obs gate during self-observation, so sampling never
pollutes the counters it samples (same trick as ``health._flush_locked``).
"""
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from metrics_tpu.obs import health as _health
from metrics_tpu.obs import registry as _reg

_SAMPLER: Optional["TelemetrySampler"] = None


def _flatten(snap: Dict[str, Dict[str, Any]]) -> Dict[Tuple[str, str], Any]:
    """``{scope: {name: value}}`` -> ``{(scope, name): value}`` for delta math."""
    flat: Dict[Tuple[str, str], Any] = {}
    for scope, counters in snap.items():
        for name, value in counters.items():
            flat[(scope, name)] = value
    return flat


class TelemetrySampler:
    """Fixed-capacity ring of periodic registry/health ticks (module docstring).

    Args:
        interval_s: target seconds between ticks (the scrape cadence).
        capacity: ticks retained — at the default 1 Hz, 600 ticks = 10 minutes
            of history in a few hundred KB of plain dicts.
        check_slos: evaluate the configured health SLO budget every tick
            (violations land in the tick AND react per the budget's action).
    """

    def __init__(
        self,
        interval_s: float = 1.0,
        capacity: int = 600,
        check_slos: bool = True,
    ) -> None:
        if interval_s <= 0:
            raise ValueError(f"interval_s must be positive, got {interval_s}")
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.interval_s = float(interval_s)
        self.capacity = int(capacity)
        self.check_slos = bool(check_slos)
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_flat = _flatten(_reg.snapshot())
        self._prev_t = time.time()
        self.ticks_taken = 0
        self.slo_violations_total = 0

    # ------------------------------------------------------------- sampling

    def tick(self) -> Dict[str, Any]:
        """Take one sample now (the thread calls this; tests call it directly
        for determinism). Returns the tick dict it appended."""
        now = time.time()
        snap_flat = _flatten(_reg.snapshot())
        counters: Dict[str, Dict[str, float]] = {}
        timers: Dict[str, Dict[str, Dict[str, float]]] = {}
        for key, value in snap_flat.items():
            scope, name = key
            prev = self._prev_flat.get(key)
            if isinstance(value, dict):
                prev = prev if isinstance(prev, dict) else {}
                d_count = value.get("count", 0) - prev.get("count", 0)
                if d_count:
                    timers.setdefault(scope, {})[name] = {
                        "count": d_count,
                        "total_s": value.get("total_s", 0.0) - prev.get("total_s", 0.0),
                    }
            else:
                delta = value - (prev if isinstance(prev, (int, float)) else 0)
                if delta:
                    counters.setdefault(scope, {})[name] = delta

        monitor = _health._MONITOR
        latency: Dict[str, Any] = {}
        hbm: Optional[int] = None
        violations: List[Dict[str, Any]] = []
        if monitor is not None:
            # report() flushes residual buffers with the obs gate suppressed,
            # so the sampler's own sketch computes never count themselves
            health_report = monitor.report()
            latency = health_report["latency_us"]
            hbm = health_report["hbm_watermark_bytes"]
            if self.check_slos:
                violations = monitor.check_slos()

        tick = {
            "t_unix": now,
            "dt_s": now - self._prev_t,
            "counters": counters,
            "timers": timers,
            "latency_us": latency,
            "hbm_watermark_bytes": hbm,
            "slo_violations": violations,
        }
        with self._lock:
            self._ring.append(tick)
            self._prev_flat = snap_flat
            self._prev_t = now
            self.ticks_taken += 1
            self.slo_violations_total += len(violations)
        return tick

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — telemetry must never kill the host
                continue

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="tmscope-sampler", daemon=True
        )
        self._thread.start()

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=join_timeout_s)
            self._thread = None

    # ------------------------------------------------------------ reporting

    def ticks(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Snapshot of the tick ring, oldest first (optionally only the last N)."""
        with self._lock:
            out = list(self._ring)
        return out if last is None else out[-last:]

    def series(self, scope: str, name: str) -> List[Tuple[float, float]]:
        """One counter's delta series as ``[(t_unix, delta), ...]`` — zeros
        included so the series is dense over the retained window."""
        out = []
        for tick in self.ticks():
            out.append((tick["t_unix"], float(tick["counters"].get(scope, {}).get(name, 0))))
        return out

    def rates(self) -> Dict[str, Dict[str, float]]:
        """Per-second counter rates off the most recent tick (empty before the
        first tick; a zero-width tick reports raw deltas)."""
        last = self.ticks(last=1)
        if not last:
            return {}
        tick = last[0]
        dt = tick["dt_s"] if tick["dt_s"] > 0 else 1.0
        return {
            scope: {name: delta / dt for name, delta in counters.items()}
            for scope, counters in tick["counters"].items()
        }


# ----------------------------------------------------------- module facade


def enable(
    interval_s: float = 1.0,
    capacity: int = 600,
    check_slos: bool = True,
    start_thread: bool = True,
    enable_obs: bool = True,
) -> TelemetrySampler:
    """Allocate and start the sampler (idempotent: replaces any previous).

    ``start_thread=False`` allocates the ring but leaves ticking to explicit
    :meth:`TelemetrySampler.tick` calls — the deterministic mode tests (and
    callers with their own scheduler) use.
    """
    global _SAMPLER
    disable()
    sampler = TelemetrySampler(
        interval_s=interval_s, capacity=capacity, check_slos=check_slos
    )
    _SAMPLER = sampler
    if enable_obs:
        _reg.enable()
    if start_thread:
        sampler.start()
    return sampler


def disable() -> None:
    """Stop the thread and free the ring (back to the zero-overhead default)."""
    global _SAMPLER
    sampler = _SAMPLER
    _SAMPLER = None
    if sampler is not None:
        sampler.stop()


def active() -> bool:
    return _SAMPLER is not None


def sampler() -> Optional[TelemetrySampler]:
    return _SAMPLER


def ticks(last: Optional[int] = None) -> List[Dict[str, Any]]:
    return _SAMPLER.ticks(last=last) if _SAMPLER is not None else []
