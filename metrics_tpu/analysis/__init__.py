"""metrics_tpu.analysis — **tmlint**, a JAX/TPU-aware static analyzer.

The paper's stateful ``Metric`` contract (``add_state``/``update``/``compute``)
has invariants no Python type checker sees: update/compute bodies must stay
traceable (no host syncs, no Python branching on traced values, no
data-dependent shapes), and state may only flow through the registry that
``ckpt/`` serializes and ``parallel/`` reduces. tmlint checks them statically:

==================  =========================================================
rule                what it catches
==================  =========================================================
TM-HOSTSYNC         ``.item()``/``float()``/numpy calls in jit-reachable code
TM-PYBRANCH         ``if``/``while``/``assert`` on traced values
TM-DYNSHAPE         ``jnp.unique``/``nonzero``/bool-mask without ``size=``
TM-RETRACE          per-call constants into jit (compile-storm hazard)
TM-STATE-UNREG      ``update`` mutates attrs never passed to ``add_state``
TM-REDUCE-MISMATCH  ``dist_reduce_fx`` the sync/re-reduce cannot honor
TM-PERSIST          array state the ckpt serializer silently drops
==================  =========================================================

Each rule is cross-linked to the ``metrics_tpu.obs`` counter that would fire
at runtime (``--explain RULE``); trace rules know the jit boundary — decorator,
``jax.jit`` call sites, the ``Metric._wrap_update`` entry — and the repo's
``_is_concrete`` guard idiom, so host-side code is not flagged.

tmlint is the AST tier; ``metrics_tpu.analysis.san`` (**tmsan**) is the
jaxpr/HLO tier that verifies its predictions against the tracer and the
compiler: abstract traces of every registered metric (TMS-* rules), the
``tmsan_costs.json`` compile-cost budget, and the waiver crosscheck. Run it
with ``--san`` (it is not imported here to keep the AST tier import-light).

CLI::

    python -m metrics_tpu.analysis metrics_tpu/
    python -m metrics_tpu.analysis --san
    python -m metrics_tpu.analysis --explain TM-RETRACE

CI fails only on findings not waived in ``tmlint_baseline.json`` (plus cost
budget breaches in ``--san`` runs).
"""
from metrics_tpu.analysis.baseline import (
    BASELINE_FILENAME,
    apply_baseline,
    default_baseline_path,
    load_baseline,
    write_baseline,
)
from metrics_tpu.analysis.findings import INTROSPECTION_RULES, RULES, Finding, Rule, explain
from metrics_tpu.analysis.runner import Report, analyze

__all__ = [
    "BASELINE_FILENAME",
    "Finding",
    "INTROSPECTION_RULES",
    "RULES",
    "Report",
    "Rule",
    "analyze",
    "apply_baseline",
    "default_baseline_path",
    "explain",
    "load_baseline",
    "write_baseline",
]
