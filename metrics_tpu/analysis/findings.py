"""tmlint rule metadata and the Finding record.

Every rule carries its cross-link to the *runtime* observability layer
(``metrics_tpu.obs``): a static finding tells you which obs counter (or
trace-time error) would fire if the flagged line actually executed on the hot
path. This is the contract the ISSUE calls "each static rule ID cross-linked to
the runtime counter name" — lint findings and fleet JSONL exports speak the
same vocabulary, so a ``TM-RETRACE`` finding on ``Foo.update`` and a nonzero
``Foo.retrace_signatures`` counter in production point at the same bug.
"""
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Rule:
    """One tmlint/tmsan rule: identity, family, and its runtime cross-link."""

    id: str
    # tmlint: "trace-safety" | "state-contract" | "retrace-hazard"
    # tmsan:  "jaxpr-trace" | "hlo-cost" | "crosscheck"
    # tmrace: "lock-discipline" | "lock-order" | "handler-safety"
    family: str
    summary: str
    #: obs counter(s) that fire at runtime for this failure class, with
    #: ``<M>`` standing for the metric class name; None when the failure
    #: manifests as a trace-time error instead of a counter.
    counter: Optional[str]
    #: what you would see at runtime if the finding is real (error type,
    #: counter increment, or silent behavior) — printed by ``--explain``.
    runtime_signal: str
    rationale: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            id="TM-HOSTSYNC",
            family="trace-safety",
            summary="host synchronization inside a jit-reachable region",
            counter=None,
            runtime_signal=(
                "TracerArrayConversionError / ConcretizationTypeError at trace time, or a "
                "silent device->host transfer that serializes the TPU pipeline (visible as "
                "gaps between tm.update/<M> XProf scopes, obs/scopes.py)"
            ),
            rationale=(
                "`.item()`, `.tolist()`, `float()/int()/bool()` on array values, and numpy\n"
                "calls all force the device to finish and copy data to the host. Inside a\n"
                "jitted region they either fail at trace time (tracers cannot be\n"
                "concretized) or — worse, on the eager-but-hot path — silently stall the\n"
                "accelerator. The paper's Metric contract requires update/compute bodies\n"
                "to stay traceable; host work belongs behind an `_is_concrete` guard\n"
                "(metrics_tpu/utils/checks.py), which tmlint recognizes and exempts."
            ),
        ),
        Rule(
            id="TM-PYBRANCH",
            family="trace-safety",
            summary="Python control flow branching on a traced value",
            counter=None,
            runtime_signal=(
                "TracerBoolConversionError at trace time (the runtime check is the "
                "contract sweep's test_local_update_is_jit_safe)"
            ),
            rationale=(
                "`if`/`while`/`assert` on an expression derived from array values calls\n"
                "`bool()` on a tracer: under jit this raises, and eagerly it host-syncs\n"
                "per step. Data-dependent control flow must use `jnp.where`/`lax.cond`,\n"
                "or sit behind an `_is_concrete` guard so tracing skips it."
            ),
        ),
        Rule(
            id="TM-DYNSHAPE",
            family="trace-safety",
            summary="data-dependent output shape inside a jit-reachable region",
            counter=None,
            runtime_signal=(
                "ConcretizationTypeError / NonConcreteBooleanIndexError at trace time; "
                "with a concrete fallback, a retrace per distinct data shape "
                "(jax.compile_events)"
            ),
            rationale=(
                "`jnp.unique`/`nonzero`/`argwhere`/`where(cond)` (single-argument) and\n"
                "boolean-mask indexing produce shapes that depend on data, which XLA\n"
                "cannot compile. JAX's escape hatch is the `size=` argument (static\n"
                "upper bound + fill); the repo-wide alternative is the padded static-\n"
                "shape kernels in ops/ (e.g. ops/clf_curve.py curve padding)."
            ),
        ),
        Rule(
            id="TM-RETRACE",
            family="retrace-hazard",
            summary="per-call constants flowing into jit (compile storm hazard)",
            counter="<M>.retraces / <M>.retrace_signatures / jax.compile_events",
            runtime_signal=(
                "obs/recompile.py increments `<MetricClass>.retraces` (per instance) and "
                "`<MetricClass>.retrace_signatures` (per class, fleet JSONL) and warns "
                "past RETRACE_WARN_THRESHOLD distinct signatures"
            ),
            rationale=(
                "A Python scalar passed to a jitted function participates in the trace as\n"
                "a fresh constant: every new value compiles a new program (the classic\n"
                "silent 100x slowdown obs/recompile.py exists to catch at runtime).\n"
                "Convert per-call scalars with `jnp.asarray`/`jnp.float32` so they become\n"
                "traced operands, or declare them in `static_argnames` when they are\n"
                "genuinely few-valued. Building `jax.jit(...)` inside a function body is\n"
                "the same hazard: each call constructs a fresh wrapper and misses the\n"
                "C++ dispatch fast path."
            ),
        ),
        Rule(
            id="TM-STATE-UNREG",
            family="state-contract",
            summary="update() mutates an attribute never registered via add_state",
            counter=None,
            runtime_signal=(
                "silent state loss: ckpt round-trip (tests/unittests/ckpt round-trip "
                "sweep) restores a metric that recomputes from defaults; parallel sync "
                "never reduces the attr"
            ),
            rationale=(
                "The Metric contract (core/metric.py add_state) is the single registry\n"
                "that ckpt/ serializes, parallel/ reduces, and reset() restores. An\n"
                "attribute assigned in update() but never registered rides along eagerly\n"
                "and then silently disappears on checkpoint restore, never syncs across\n"
                "hosts, and survives reset() — the RASE/RMSE-SW lazy-init bug class\n"
                "fixed in PR 2. Register it with add_state, or derive it from registered\n"
                "state."
            ),
        ),
        Rule(
            id="TM-REDUCE-MISMATCH",
            family="state-contract",
            summary="dist_reduce_fx inconsistent with the state's default/shape",
            counter="<M>.syncs",
            runtime_signal=(
                "wrong values after cross-host sync (parallel/collective.py) or a "
                "checkpoint topology change (ckpt/restore.py re-reduce refuses or "
                "mis-reduces the state)"
            ),
            rationale=(
                "The reduction declared at add_state time is what parallel/collective.py\n"
                "applies on sync and what ckpt/restore.py re-applies when restoring onto\n"
                "a different host count. A `cat` reduction on a dense array default, a\n"
                "sum/mean/max/min on a list default, a `mean` over an integer-dtype\n"
                "state, or a custom callable (which the topology re-reduce cannot\n"
                "invert) all produce states the rest of the system cannot honor."
            ),
        ),
        Rule(
            id="TM-PERSIST",
            family="state-contract",
            summary="array state the ckpt serializer would silently drop",
            counter="ckpt.bytes",
            runtime_signal=(
                "ckpt.saves succeeds but ckpt.bytes is missing the attr's payload; "
                "restore_checkpoint validates only registered states, so the drop is "
                "silent"
            ),
            rationale=(
                "ckpt/serializer.py snapshots exactly the add_state registry. An array-\n"
                "valued instance attribute outside the registry (and not a constructor\n"
                "knob named in `_update_signature_attrs`, which is re-derived at\n"
                "construction, nor a declared `_ckpt_exempt_attrs` entry) holds real\n"
                "accumulated data that a preemption would lose. Register it, derive it\n"
                "from registered state, or declare the exemption explicitly."
            ),
        ),
        Rule(
            id="TMS-CALLBACK",
            family="jaxpr-trace",
            summary="host callback primitive in a supposedly device-pure graph",
            counter="san.callbacks",
            runtime_signal=(
                "every execution of the compiled program round-trips to the host "
                "(pure_callback/io_callback/debug_callback): the TPU pipeline stalls "
                "per call — visible as gaps between tm.update/<M> XProf scopes"
            ),
            rationale=(
                "tmlint's TM-HOSTSYNC works on source text; a callback can still reach\n"
                "the traced graph through a waiver, a modeling gap, or a third-party\n"
                "helper. tmsan looks at the ground truth: the closed jaxpr of every\n"
                "registered metric's update/compute traced under abstract inputs. A\n"
                "`pure_callback`/`io_callback`/`debug_callback` equation there means\n"
                "host code runs on EVERY step of the hot path, not just at trace time.\n"
                "Move the work onto the device, or declare the class `_host_side_update`."
            ),
        ),
        Rule(
            id="TMS-F64",
            family="jaxpr-trace",
            summary="float64 value or constant in the traced graph",
            counter="san.f64",
            runtime_signal=(
                "on TPU: 2x HBM for the affected buffers and software-emulated f64 "
                "arithmetic (or an XLA error on platforms without f64 support)"
            ),
            rationale=(
                "With jax's default x64-disabled config a float64 aval cannot appear\n"
                "unless code opts in (`jax.experimental.enable_x64`, explicit f64\n"
                "dtypes). A silent promotion — typically an np.float64 scalar or a\n"
                "strongly-typed f64 constant leaking into arithmetic — doubles state\n"
                "bytes and falls off the TPU fast path. Use weak python scalars or\n"
                "explicit f32/bf16 dtypes."
            ),
        ),
        Rule(
            id="TMS-UPCAST",
            family="jaxpr-trace",
            summary="bf16/f16 state silently promoted to a wider dtype by update",
            counter="san.upcasts",
            runtime_signal=(
                "state_report() shows f32 buffers where bf16 was declared (2x HBM); a "
                "checkpoint saved after the first update fails restore validation "
                "against the declared default dtype (ckpt DtypeDrift)"
            ),
            rationale=(
                "A metric cast to bf16 (`set_dtype(jnp.bfloat16)`) must keep its state\n"
                "bf16 through update: the state transition's output dtype is part of\n"
                "the Metric contract (ckpt manifests validate it; parallel sync\n"
                "reduces it). A strongly-typed f32 scalar (np.float32(x),\n"
                "jnp.float32(x), jnp.asarray(x, jnp.float32)) in the accumulation\n"
                "promotes the whole state. Use weak python scalars or\n"
                "`.astype(state.dtype)` so the declared dtype survives. (Deliberate\n"
                "f32 accumulation is fine — declare the STATE f32 then.)"
            ),
        ),
        Rule(
            id="TMS-BIGCONST",
            family="jaxpr-trace",
            summary="large constant baked into the traced graph",
            counter="san.bigconsts",
            runtime_signal=(
                "per-executable HBM for the baked constant (jax.live_arrays shows a "
                "copy per compiled program) and re-materialization on every retrace "
                "(<M>.retraces / jax.compile_events)"
            ),
            rationale=(
                "A constant above the byte threshold captured by the trace (a numpy\n"
                "table, a materialized iota/linspace grid, a dense helper matrix) is\n"
                "embedded in the XLA executable: it costs HBM per program, transfer\n"
                "per compile, and is rebuilt on every retrace. Pass it as a traced\n"
                "operand (donated state or argument), or compute it on device from\n"
                "cheap primitives (iota) inside the graph."
            ),
        ),
        Rule(
            id="TMS-COLLECTIVE",
            family="jaxpr-trace",
            summary="collective over an axis not bound in the traced context",
            counter="san.collectives",
            runtime_signal=(
                "NameError: unbound axis name at trace time inside shard_map/pmap, or "
                "a deadlock when a single-host path reaches a collective only some "
                "hosts execute"
            ),
            rationale=(
                "psum/all_gather/ppermute equations in a graph traced WITHOUT a mesh\n"
                "context mean a collective is reachable from a single-host code path:\n"
                "under real sharding some hosts would enter it and others not —\n"
                "the classic SPMD deadlock. Collectives belong in sync_state/\n"
                "compute_from(axis_name=...) where the axis is explicitly bound\n"
                "(parallel/collective.py), never in local_update."
            ),
        ),
        Rule(
            id="TMS-DYNSHAPE",
            family="jaxpr-trace",
            summary="metric body failed to trace (dynamic shape / concretization)",
            counter="san.trace_failures",
            runtime_signal=(
                "TracerBoolConversionError / ConcretizationTypeError / "
                "NonConcreteBooleanIndexError the first time the metric meets "
                "jit/shard_map in production"
            ),
            rationale=(
                "tmsan actually traces every registered metric's update/compute under\n"
                "abstract ShapeDtypeStruct inputs — the same thing jit does. A trace\n"
                "failure here is ground truth that the body is not trace-safe, and a\n"
                "finding tmlint's AST tier should have predicted (TM-PYBRANCH/\n"
                "TM-DYNSHAPE): this rule is the should-be-empty verification that the\n"
                "two tiers agree. Fix the metric (size= bounds, lax.cond, padded ops/\n"
                "kernels) or declare it `_host_side_update` if host-side by contract."
            ),
        ),
        Rule(
            id="TMS-LINTGAP",
            family="crosscheck",
            summary="jaxpr-level host callback in a tmlint-clean function",
            counter="san.lintgaps",
            runtime_signal=(
                "same as TMS-CALLBACK — but additionally means tmlint's TM-HOSTSYNC "
                "model has a blind spot worth closing"
            ),
            rationale=(
                "The two analysis tiers keep each other honest: every callback tmsan\n"
                "finds in a traced graph must correspond to a TM-HOSTSYNC finding (or\n"
                "waiver) at the same source location. A callback in a function tmlint\n"
                "considered clean is a LINTGAP — fix the metric AND extend the AST\n"
                "rule (trace_rules.py) so the cheap tier catches the pattern next time."
            ),
        ),
        Rule(
            id="TMS-STALE-WAIVER",
            family="crosscheck",
            summary="TM-HOSTSYNC waiver contradicted by jaxpr evidence",
            counter="san.stale_waivers",
            runtime_signal=(
                "the waived 'host-only' line participates in traced graphs — the "
                "waiver's safety claim no longer holds and the original TM-HOSTSYNC "
                "runtime signal applies"
            ),
            rationale=(
                "A TM-HOSTSYNC waiver asserts the flagged host work stays off traced\n"
                "paths (eager-only tier, concreteness guard). tmsan corroborates each\n"
                "waiver against the traced footprint: the waived lines must be absent\n"
                "from every traced jaxpr (corroborated-by-absence) or appear as an\n"
                "explicit callback (corroborated-by-presence). A waived line showing\n"
                "up as ordinary traced computation means the code moved under the\n"
                "waiver — re-triage it."
            ),
        ),
        Rule(
            id="TMR-UNLOCKED",
            family="lock-discipline",
            summary="shared attribute mutated from >=2 thread roles outside its governing lock",
            counter="race.unlocked",
            runtime_signal=(
                "lost updates / torn compound state under real concurrency: a counter "
                "that undercounts, a dict whose check-then-act interleaves — the exact-total "
                "stress tests (pytest -m race) are the dynamic corroboration"
            ),
            rationale=(
                "An attribute written by two different thread roles (user thread, ingest\n"
                "ticker, ckpt writer, sampler, prom handler, ...) needs ONE governing\n"
                "lock covering every non-atomic mutation. tmrace infers governance from\n"
                "the acquisition context of each write (interprocedural: the held-at-\n"
                "entry set is the intersection over call sites, or an explicit\n"
                "`@locked_by(...)` contract) and flags targets where some mutation runs\n"
                "outside every candidate lock. The documented GIL-atomic idioms are\n"
                "modeled as atomic and never flagged: a single `deque.append` (the\n"
                "obs/ring.py hot path), a single attribute store of a fresh object,\n"
                "`Event.set/clear`. Read-modify-write (`+=`, `x = x + ...`) and\n"
                "multi-step container surgery are not atomic and need the lock."
            ),
        ),
        Rule(
            id="TMR-ORDER",
            family="lock-order",
            summary="cycle in the interprocedural lock-acquisition order graph",
            counter="race.order_cycles",
            runtime_signal=(
                "a deadlock under the right interleaving: two threads each holding one "
                "lock of the cycle and blocking on the next — the process wedges with no "
                "exception, visible only as a stalled tick/scrape/save"
            ),
            rationale=(
                "tmrace records an edge L1 -> L2 whenever code acquires L2 while\n"
                "holding L1 — including interprocedurally (a call made under L1 to a\n"
                "function that transitively acquires L2). A cycle in that graph means\n"
                "two code paths take the same locks in opposite orders, which is a\n"
                "deadlock waiting for the right preemption point. Fix by ordering the\n"
                "acquisitions consistently (the repo convention: never call into\n"
                "another locked subsystem while holding your own lock — snapshot under\n"
                "the lock, work outside it, e.g. ckpt secure_pending_snapshots)."
            ),
        ),
        Rule(
            id="TMR-HOLD-HOST",
            family="lock-discipline",
            summary="device sync or disk I/O while holding a lock",
            counter="race.hold_host",
            runtime_signal=(
                "latency cliffs on every thread contending the lock: an enqueue/scrape/"
                "tick blocked behind a listdir or a device->host transfer — shows up as "
                "p99 spikes in health latency and gaps between ticks in the tmscope series"
            ),
            rationale=(
                "A lock held across host-blocking work (`os.listdir`, `open`/`fsync`,\n"
                "`time.sleep`, a `block_until_ready` device sync, `np.asarray` on\n"
                "device values,\n"
                "thread `.join`) serializes every contending thread behind IO the lock\n"
                "was never meant to cover. Hot-path locks (ingest `_admit`, the\n"
                "registry lock) must only guard memory ops: move the IO outside the\n"
                "critical section (snapshot-then-write) or keep a dedicated coarse\n"
                "lock for the slow path and document it with a waiver."
            ),
        ),
        Rule(
            id="TMR-HANDLER",
            family="handler-safety",
            summary="signal/atexit/excepthook code blocking on a lock or mutating shared state",
            counter="race.handler",
            runtime_signal=(
                "a dump-on-preemption that deadlocks: the signal arrives while the "
                "preempted thread holds the lock the handler then blocks on — the process "
                "dies silently with NO flight dump, defeating the post-mortem"
            ),
            rationale=(
                "Signal handlers run ON TOP of a preempted thread; atexit/excepthook run\n"
                "while daemon threads may be mid-critical-section. Any blocking\n"
                "`lock.acquire()` (including `with lock:`) reachable from handler\n"
                "context can therefore wait on a holder that will never resume —\n"
                "self-deadlock. Handler paths must use try-lock\n"
                "(`acquire(blocking=False)`) with a lock-free fallback (the flight\n"
                "recorder's ring snapshot is the model), and must not perform\n"
                "non-atomic mutations of state other threads read."
            ),
        ),
        Rule(
            id="TMR-LEAK",
            family="lock-discipline",
            summary="thread spawned without a daemon flag or join/close path",
            counter="race.leaks",
            runtime_signal=(
                "process refuses to exit (non-daemon thread still parked in wait) or "
                "threads accumulate across restarts — visible as a hanging test run or "
                "a climbing thread count in the health report"
            ),
            rationale=(
                "Every `threading.Thread(...)` the library starts must either be a\n"
                "daemon (`daemon=True` — dies with the process, the repo default for\n"
                "tickers/writers/samplers) or have an owned join/close path (the handle\n"
                "is stored and `.join()`ed by a close()/stop() method). A spawn with\n"
                "neither leaks: it pins the interpreter at exit and accumulates under\n"
                "restart churn."
            ),
        ),
        Rule(
            id="TMO-DONATE-ALIAS",
            family="buffer-ownership",
            summary="possibly-host-aliasing buffer reaches a donated argument position",
            counter="own.donate_alias",
            runtime_signal=(
                "intermittent SIGSEGV/SIGBUS (heap corruption) when the donating "
                "executable was deserialized from the persistent compile cache — the "
                "exact PR 16 restore-path crash (~40-88% reproducible under "
                "concurrent tick load, invisible in single-threaded tests)"
            ),
            rationale=(
                "`jnp.asarray` over a host numpy array (an `np.frombuffer` payload\n"
                "view, a `memoryview`, any np-allocated buffer) can produce a\n"
                "ZERO-COPY device buffer aliasing host memory on the CPU backend.\n"
                "Donating such a buffer (`donate_argnums`) hands XLA memory it does\n"
                "not own: with a freshly-traced executable this happens to work, but\n"
                "an executable deserialized from the persistent compilation cache\n"
                "writes through the alias and corrupts the heap (the PR 16 triple:\n"
                "numpy-backed restored state x disk-cache executable x donation).\n"
                "Materialize an owning copy first: `jnp.array(x, copy=True)` — the\n"
                "`ckpt.restore._owned` idiom — or `.copy()` on the device array."
            ),
        ),
        Rule(
            id="TMO-USE-AFTER-DONATE",
            family="buffer-ownership",
            summary="donated state read on a path after the donating call, before re-pointing",
            counter="own.use_after_donate",
            runtime_signal=(
                "jax raises `Array has been deleted` on the read — or, in an "
                "exception path, a recovery handler silently re-points live state at "
                "deleted buffers and the next compute returns garbage"
            ),
            rationale=(
                "A donated input buffer is DELETED by the launch: every read of the\n"
                "donated name after the call observes a dead array until the name is\n"
                "re-pointed at the executable's returned buffers. The sanctioned\n"
                "exception-path idiom is the ingest/fused recovery handler: probe\n"
                "`.is_deleted()` first and raise `_DonatedStateLost` when the\n"
                "donation already consumed the buffers — handlers that consult\n"
                "`is_deleted` are recognized and exempt (the runtime probe is the\n"
                "dynamic twin of this static rule)."
            ),
        ),
        Rule(
            id="TMO-DOUBLE-DONATE",
            family="buffer-ownership",
            summary="one value reachable at two donated positions of one call",
            counter="own.double_donate",
            runtime_signal=(
                "XLA rejects the launch (`Donation of buffer ... already donated`) "
                "or — through two pytree leaves sharing one buffer — writes the same "
                "HBM twice, corrupting whichever accumulation lands first"
            ),
            rationale=(
                "XLA donation is per-buffer: the same underlying buffer arriving at\n"
                "two donated positions (the same name passed twice, or two state\n"
                "leaves aliasing one array after manual state surgery) is either\n"
                "rejected at dispatch or silently double-written. The repo's\n"
                "sanctioned pass is `FusedCollectionUpdate._donation_guard`, which\n"
                "dedups by `id(leaf)` and copies the second occurrence — donating\n"
                "call sites dominated by the guard are exempt."
            ),
        ),
        Rule(
            id="TMO-SNAPSHOT-GAP",
            family="buffer-ownership",
            summary="donating call not dominated by the snapshot-before-donate guard",
            counter="own.snapshot_gap",
            runtime_signal=(
                "an async checkpoint racing the donation serializes deleted buffers: "
                "`ckpt.saves` commits a step whose payload CRCs were computed over "
                "freed memory — restore later fails Corrupt, or worse, restores noise"
            ),
            rationale=(
                "Async checkpointing snapshots immutable array REFERENCES and\n"
                "materializes device->host lazily on the writer thread. A donation\n"
                "deletes those arrays in place, so every donating call site must\n"
                "first materialize in-flight snapshot entries that reference the\n"
                "about-to-be-donated buffers: `ckpt.manager.secure_pending_snapshots`\n"
                "(via `_secure_ckpt_snapshots` / `_shield_donation`). A donating\n"
                "launch with no dominating snapshot guard races the ckpt writer."
            ),
        ),
        Rule(
            id="TMO-KEY-GAP",
            family="buffer-ownership",
            summary="executable-cache key omits an input the cached program depends on",
            counter="own.key_gap",
            runtime_signal=(
                "a stale-cache hit: the engine replays an executable compiled for a "
                "different closed-over value — wrong results with no error, or an "
                "aval mismatch crash at dispatch (`Argument types differ`)"
            ),
            rationale=(
                "An AOT executable cache (`self._cache[key] = jitted.lower(...)\n"
                ".compile()`) is only sound when `key` covers everything the compiled\n"
                "program was specialized on: the avals of every runtime argument AND\n"
                "every static value the traced step function closes over (builder\n"
                "arguments, static specs). An argument or closure input missing from\n"
                "the key means two call sites with different values share one\n"
                "executable — the stale-cache hazard the fused/fleet/ingest key\n"
                "tuples (`_aval_key`/`_static_key` components) exist to prevent."
            ),
        ),
        Rule(
            id="TMO-ENGINE-DRIFT",
            family="engine-contract",
            summary="launch-engine donation ladder diverges from the shared contract",
            counter="own.engine_drift",
            runtime_signal=(
                "a hazard fixed in one engine recurs in another: e.g. a snapshot-"
                "before-donate fix landed in fused but not ingest shows up as the "
                "same ckpt corruption, months later, in a different code path"
            ),
            rationale=(
                "fused, fleet, ingest, and the rank dispatch each hand-roll the same\n"
                "launch contract: donation shielding (default-copy + dedup +\n"
                "snapshot-before-donate), a keyed executable cache, demote-on-failure,\n"
                "and warm-manifest record/replay. tmown extracts each engine's\n"
                "implementation of every contract component and flags divergence —\n"
                "a component present in most engines but missing (or differently\n"
                "shaped) in one. The full per-engine component matrix is written to\n"
                "`tmown_engine_drift.json`: it is the design worksheet for ROADMAP\n"
                "item 5 (the unified serve/engine.py must absorb exactly these\n"
                "divergences). Waive entries that are by-design until that refactor."
            ),
        ),
        Rule(
            id="TMS-BUDGET",
            family="hlo-cost",
            summary="compiled cost grew >15% over the checked-in budget",
            counter="san.budget_breaches",
            runtime_signal=(
                "the next benchmark run regresses (BENCH flops/bytes-bound configs); "
                "tmsan catches it statically from .compile().cost_analysis() before "
                "any benchmark executes"
            ),
            rationale=(
                "tmsan_costs.json records flops / bytes-accessed / peak transient\n"
                "bytes per (metric, canonical shape) from XLA's own cost model. A\n"
                ">15% unexplained growth is a static perf regression — an accidental\n"
                "broadcast, a lost fusion, a dtype widening — caught before a\n"
                "benchmark ever runs. If the growth is intended (new feature, better\n"
                "accuracy), refresh the budget: `python -m metrics_tpu.analysis --san\n"
                "--write-costs` and commit the diff with the explanation."
            ),
        ),
        Rule(
            id="TMH-AXIS-UNBOUND",
            family="axis-binding",
            summary="collective over an axis name no reaching mapped context binds",
            counter="shard.axis_unbound",
            runtime_signal=(
                "NameError: unbound axis name at trace time when the function is "
                "reached outside a map; under a *different* mesh, a silent wrong-"
                "world reduction (tmsan's TMS-COLLECTIVE is the jaxpr-level twin: "
                "it sees the trace, tmshard sees every call path statically)"
            ),
            rationale=(
                "`psum(x, 'data')` only means something inside a shard_map/pmap whose\n"
                "mesh binds 'data'. tmshard's bound-axis fixpoint intersects the axis\n"
                "names guaranteed bound over every caller chain (mapped bodies are\n"
                "pinned to their entry's mesh axes; a dynamic mesh pins to TOP, which\n"
                "never flags): a literal axis outside that set means some reaching\n"
                "path traces the collective with the axis unbound. Fix by threading\n"
                "the axis name from the mapped entry (the `axis_name=` parameter\n"
                "idiom every parallel/collective.py helper uses), or by mapping the\n"
                "function before calling it."
            ),
        ),
        Rule(
            id="TMH-SPEC-ALGEBRA",
            family="spec-algebra",
            summary="state reduction algebra incompatible with its partition spec",
            counter="shard.spec_algebra",
            runtime_signal=(
                "silent wrong results: psum over the partitioned axis double-counts "
                "(each shard holds *distinct* rows, not replicas); the runtime twin "
                "is TM-REDUCE-MISMATCH's merge-vs-sync divergence, and the contract "
                "sweep's sharded-vs-single-device equality tests catch it only for "
                "covered classes"
            ),
            rationale=(
                "A shard_map in-spec `P('data')` means each shard owns a distinct\n"
                "block of rows. Reducing that operand *over the same axis* with\n"
                "psum/pmean/pmax/pmin mixes different logical rows — the classic\n"
                "double-count. The legal idiom reduces the local block first\n"
                "(`x.sum(axis=0)`), producing a replica-shaped value, then syncs;\n"
                "or gathers with all_gather when rows must survive. The shard-plan\n"
                "worksheet (tmshard_state_plan.json) records which reduction each\n"
                "registered state declares so the item-1 sharded-state design can\n"
                "pick legal axes per state family."
            ),
        ),
        Rule(
            id="TMH-REPLICA-DIVERGE",
            family="axis-binding",
            summary="replica-divergent host value inside a mapped trace or collective",
            counter="shard.replica_diverge",
            runtime_signal=(
                "collective deadlock (replicas disagree on trace constants and "
                "compile different programs) or a silent per-replica result skew; "
                "multi-host, the hang surfaces as a DCN barrier timeout"
            ),
            rationale=(
                "`jax.process_index()`, wall clock reads, host RNG, and\n"
                "`len(jax.devices())` return different values per process. Traced\n"
                "under shard_map/pmap they become per-replica *constants*: every\n"
                "replica compiles a different program, and the first collective\n"
                "either deadlocks or combines incomparable values. Hoist the host\n"
                "read into the eager launcher and pass the value in as an operand\n"
                "(how parallel/collective.py's process_topology is consumed), or\n"
                "derive replica identity inside the trace with `jax.lax.axis_index`."
            ),
        ),
        Rule(
            id="TMH-DONATE-RESHARD",
            family="spec-algebra",
            summary="buffer donated into a launch whose in-spec differs from its placement",
            counter="shard.donate_reshard",
            runtime_signal=(
                "no error: XLA inserts a resharding copy, the donated buffer is "
                "consumed by the *copy*, and peak HBM stays at two live buffers — "
                "visible only as the donation saving never materializing "
                "(obs buffer stats; tmown's TMO-DONATE-ALIAS lattice is the "
                "host-memory sibling of this device-placement facet)"
            ),
            rationale=(
                "Donation frees the input buffer only when XLA can reuse it in\n"
                "place, which requires the argument's sharding to match the\n"
                "executable's in-spec. `device_put(x, NamedSharding(mesh, P('data')))`\n"
                "followed by a donating jit with `in_shardings=P(None)` silently\n"
                "copies-to-reshard first: the donation is dead, and a state buffer\n"
                "sized near one chip's HBM (the ROADMAP item 1 target) OOMs where\n"
                "the un-donated math said it fits. Align the placement with the\n"
                "launch spec, or drop the misleading donate_argnums."
            ),
        ),
        Rule(
            id="TMH-KEY-SHARD",
            family="mesh-contract",
            summary="executable-cache key lacks a sharding/mesh facet for placed inputs",
            counter="shard.key_shard",
            runtime_signal=(
                "stale-executable replay after a mesh or placement change: output "
                "placed on the wrong devices, or an XLA donation/layout error deep "
                "in serving — the same failure class TMO-KEY-GAP guards for shapes, "
                "one facet further (feeds ROADMAP item 5's unified engine key)"
            ),
            rationale=(
                "The four launch engines key their AOT caches on aval shapes/dtypes\n"
                "and static config. Once inputs are *placed* arrays, two calls with\n"
                "identical avals but different shardings must not share an\n"
                "executable: the compiled program bakes in the input sharding.\n"
                "Any cache consuming placed arrays needs a sharding/mesh/topology\n"
                "component in its key (core/fused.py `_aval_key` now appends the\n"
                "NamedSharding spec for committed non-replicated inputs — the\n"
                "engine-shared facet this rule checks for)."
            ),
        ),
        Rule(
            id="TMH-MESH-DRIFT",
            family="mesh-contract",
            summary="launch engine missing a mesh-awareness component its siblings have",
            counter="shard.mesh_drift",
            runtime_signal=(
                "none directly — the drift is the *absence* of machinery: the "
                "engine without the component fails later (stale executable, "
                "unsharded launch, missing topology seed) exactly where its "
                "siblings survive; TMO-ENGINE-DRIFT is the ownership-facet analog"
            ),
            rationale=(
                "fused, fleet, ingest, the rank dispatch, and the shard_map serving\n"
                "program in parallel/mesh.py each grew their own slice of SPMD\n"
                "machinery (axis binding, collective sync, spec plumbing, placed\n"
                "I/O, sharded cache keys, topology seeding). A component present in\n"
                ">=2 engines but absent in another is either a latent gap the\n"
                "item-1/item-4 designs must fill, or a deliberate exemption worth a\n"
                "waiver with its reason. The matrix is embedded in\n"
                "tmshard_state_plan.json (`engine_mesh_matrix`), regenerated by\n"
                "`--shard --write-plan` and kept in sync by test."
            ),
        ),
    )
}

#: Rules that need the import-time introspection pass (vs pure AST).
INTROSPECTION_RULES: Tuple[str, ...] = ("TM-STATE-UNREG", "TM-REDUCE-MISMATCH", "TM-PERSIST")

#: tmsan (jaxpr/HLO tier) rules — produced by ``metrics_tpu.analysis.san``, not
#: by the AST pass. Baseline waivers are shared but scoped: each tier applies
#: (and reports staleness for) only the waivers in its own namespace.
SAN_RULES: Tuple[str, ...] = (
    "TMS-CALLBACK", "TMS-F64", "TMS-UPCAST", "TMS-BIGCONST",
    "TMS-COLLECTIVE", "TMS-DYNSHAPE", "TMS-LINTGAP", "TMS-STALE-WAIVER",
    "TMS-BUDGET",
)

#: tmrace (concurrency tier) rules — produced by ``metrics_tpu.analysis.race``.
RACE_RULES: Tuple[str, ...] = (
    "TMR-UNLOCKED", "TMR-ORDER", "TMR-HOLD-HOST", "TMR-HANDLER", "TMR-LEAK",
)

#: tmown (buffer-ownership tier) rules — produced by ``metrics_tpu.analysis.own``.
OWN_RULES: Tuple[str, ...] = (
    "TMO-DONATE-ALIAS", "TMO-USE-AFTER-DONATE", "TMO-DOUBLE-DONATE",
    "TMO-SNAPSHOT-GAP", "TMO-KEY-GAP", "TMO-ENGINE-DRIFT",
)

#: tmshard (sharding/collective tier) rules — ``metrics_tpu.analysis.shard``.
SHARD_RULES: Tuple[str, ...] = (
    "TMH-AXIS-UNBOUND", "TMH-SPEC-ALGEBRA", "TMH-REPLICA-DIVERGE",
    "TMH-DONATE-RESHARD", "TMH-KEY-SHARD", "TMH-MESH-DRIFT",
)

#: AST/introspection (tmlint) rules — everything not owned by another tier.
LINT_RULES: Tuple[str, ...] = tuple(
    r for r in RULES
    if r not in SAN_RULES and r not in RACE_RULES and r not in OWN_RULES
    and r not in SHARD_RULES
)


@dataclass
class Finding:
    """One lint finding, anchored to a repo-relative path and symbol.

    Baseline waivers match on ``(rule, path, symbol)`` — deliberately not the
    line number, so waived findings do not churn when unrelated edits shift
    lines in the file.
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    symbol: str  # dotted context: "func", "Class.update", "Class.state_name"
    message: str
    waived: bool = False
    waive_reason: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        mark = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.symbol}] {self.message}{mark}"


def explain(rule_id: str) -> str:
    """Human ``--explain`` text for one rule (raises KeyError on unknown ids)."""
    r = RULES[rule_id]
    counter = r.counter if r.counter else "none — fails at trace time instead of counting"
    return (
        f"{r.id} ({r.family}): {r.summary}\n"
        f"\nobs counter: {counter}"
        f"\nruntime signal: {r.runtime_signal}\n"
        f"\n{r.rationale}\n"
        "\nWaiving: add {\"rule\": \"" + r.id + "\", \"path\": \"<repo-relative file>\","
        " \"symbol\": \"<symbol>\", \"reason\": \"<why this is safe>\"} to"
        " tmlint_baseline.json (see `python -m metrics_tpu.analysis --help`)."
    )
