"""tmlint rule metadata and the Finding record.

Every rule carries its cross-link to the *runtime* observability layer
(``metrics_tpu.obs``): a static finding tells you which obs counter (or
trace-time error) would fire if the flagged line actually executed on the hot
path. This is the contract the ISSUE calls "each static rule ID cross-linked to
the runtime counter name" — lint findings and fleet JSONL exports speak the
same vocabulary, so a ``TM-RETRACE`` finding on ``Foo.update`` and a nonzero
``Foo.retrace_signatures`` counter in production point at the same bug.
"""
from dataclasses import dataclass
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class Rule:
    """One tmlint rule: identity, family, and its runtime cross-link."""

    id: str
    family: str  # "trace-safety" | "state-contract" | "retrace-hazard"
    summary: str
    #: obs counter(s) that fire at runtime for this failure class, with
    #: ``<M>`` standing for the metric class name; None when the failure
    #: manifests as a trace-time error instead of a counter.
    counter: Optional[str]
    #: what you would see at runtime if the finding is real (error type,
    #: counter increment, or silent behavior) — printed by ``--explain``.
    runtime_signal: str
    rationale: str


RULES: Dict[str, Rule] = {
    r.id: r
    for r in (
        Rule(
            id="TM-HOSTSYNC",
            family="trace-safety",
            summary="host synchronization inside a jit-reachable region",
            counter=None,
            runtime_signal=(
                "TracerArrayConversionError / ConcretizationTypeError at trace time, or a "
                "silent device->host transfer that serializes the TPU pipeline (visible as "
                "gaps between tm.update/<M> XProf scopes, obs/scopes.py)"
            ),
            rationale=(
                "`.item()`, `.tolist()`, `float()/int()/bool()` on array values, and numpy\n"
                "calls all force the device to finish and copy data to the host. Inside a\n"
                "jitted region they either fail at trace time (tracers cannot be\n"
                "concretized) or — worse, on the eager-but-hot path — silently stall the\n"
                "accelerator. The paper's Metric contract requires update/compute bodies\n"
                "to stay traceable; host work belongs behind an `_is_concrete` guard\n"
                "(metrics_tpu/utils/checks.py), which tmlint recognizes and exempts."
            ),
        ),
        Rule(
            id="TM-PYBRANCH",
            family="trace-safety",
            summary="Python control flow branching on a traced value",
            counter=None,
            runtime_signal=(
                "TracerBoolConversionError at trace time (the runtime check is the "
                "contract sweep's test_local_update_is_jit_safe)"
            ),
            rationale=(
                "`if`/`while`/`assert` on an expression derived from array values calls\n"
                "`bool()` on a tracer: under jit this raises, and eagerly it host-syncs\n"
                "per step. Data-dependent control flow must use `jnp.where`/`lax.cond`,\n"
                "or sit behind an `_is_concrete` guard so tracing skips it."
            ),
        ),
        Rule(
            id="TM-DYNSHAPE",
            family="trace-safety",
            summary="data-dependent output shape inside a jit-reachable region",
            counter=None,
            runtime_signal=(
                "ConcretizationTypeError / NonConcreteBooleanIndexError at trace time; "
                "with a concrete fallback, a retrace per distinct data shape "
                "(jax.compile_events)"
            ),
            rationale=(
                "`jnp.unique`/`nonzero`/`argwhere`/`where(cond)` (single-argument) and\n"
                "boolean-mask indexing produce shapes that depend on data, which XLA\n"
                "cannot compile. JAX's escape hatch is the `size=` argument (static\n"
                "upper bound + fill); the repo-wide alternative is the padded static-\n"
                "shape kernels in ops/ (e.g. ops/clf_curve.py curve padding)."
            ),
        ),
        Rule(
            id="TM-RETRACE",
            family="retrace-hazard",
            summary="per-call constants flowing into jit (compile storm hazard)",
            counter="<M>.retraces / <M>.retrace_signatures / jax.compile_events",
            runtime_signal=(
                "obs/recompile.py increments `<MetricClass>.retraces` (per instance) and "
                "`<MetricClass>.retrace_signatures` (per class, fleet JSONL) and warns "
                "past RETRACE_WARN_THRESHOLD distinct signatures"
            ),
            rationale=(
                "A Python scalar passed to a jitted function participates in the trace as\n"
                "a fresh constant: every new value compiles a new program (the classic\n"
                "silent 100x slowdown obs/recompile.py exists to catch at runtime).\n"
                "Convert per-call scalars with `jnp.asarray`/`jnp.float32` so they become\n"
                "traced operands, or declare them in `static_argnames` when they are\n"
                "genuinely few-valued. Building `jax.jit(...)` inside a function body is\n"
                "the same hazard: each call constructs a fresh wrapper and misses the\n"
                "C++ dispatch fast path."
            ),
        ),
        Rule(
            id="TM-STATE-UNREG",
            family="state-contract",
            summary="update() mutates an attribute never registered via add_state",
            counter=None,
            runtime_signal=(
                "silent state loss: ckpt round-trip (tests/unittests/ckpt round-trip "
                "sweep) restores a metric that recomputes from defaults; parallel sync "
                "never reduces the attr"
            ),
            rationale=(
                "The Metric contract (core/metric.py add_state) is the single registry\n"
                "that ckpt/ serializes, parallel/ reduces, and reset() restores. An\n"
                "attribute assigned in update() but never registered rides along eagerly\n"
                "and then silently disappears on checkpoint restore, never syncs across\n"
                "hosts, and survives reset() — the RASE/RMSE-SW lazy-init bug class\n"
                "fixed in PR 2. Register it with add_state, or derive it from registered\n"
                "state."
            ),
        ),
        Rule(
            id="TM-REDUCE-MISMATCH",
            family="state-contract",
            summary="dist_reduce_fx inconsistent with the state's default/shape",
            counter="<M>.syncs",
            runtime_signal=(
                "wrong values after cross-host sync (parallel/collective.py) or a "
                "checkpoint topology change (ckpt/restore.py re-reduce refuses or "
                "mis-reduces the state)"
            ),
            rationale=(
                "The reduction declared at add_state time is what parallel/collective.py\n"
                "applies on sync and what ckpt/restore.py re-applies when restoring onto\n"
                "a different host count. A `cat` reduction on a dense array default, a\n"
                "sum/mean/max/min on a list default, a `mean` over an integer-dtype\n"
                "state, or a custom callable (which the topology re-reduce cannot\n"
                "invert) all produce states the rest of the system cannot honor."
            ),
        ),
        Rule(
            id="TM-PERSIST",
            family="state-contract",
            summary="array state the ckpt serializer would silently drop",
            counter="ckpt.bytes",
            runtime_signal=(
                "ckpt.saves succeeds but ckpt.bytes is missing the attr's payload; "
                "restore_checkpoint validates only registered states, so the drop is "
                "silent"
            ),
            rationale=(
                "ckpt/serializer.py snapshots exactly the add_state registry. An array-\n"
                "valued instance attribute outside the registry (and not a constructor\n"
                "knob named in `_update_signature_attrs`, which is re-derived at\n"
                "construction, nor a declared `_ckpt_exempt_attrs` entry) holds real\n"
                "accumulated data that a preemption would lose. Register it, derive it\n"
                "from registered state, or declare the exemption explicitly."
            ),
        ),
    )
}

#: Rules that need the import-time introspection pass (vs pure AST).
INTROSPECTION_RULES: Tuple[str, ...] = ("TM-STATE-UNREG", "TM-REDUCE-MISMATCH", "TM-PERSIST")


@dataclass
class Finding:
    """One lint finding, anchored to a repo-relative path and symbol.

    Baseline waivers match on ``(rule, path, symbol)`` — deliberately not the
    line number, so waived findings do not churn when unrelated edits shift
    lines in the file.
    """

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    col: int
    symbol: str  # dotted context: "func", "Class.update", "Class.state_name"
    message: str
    waived: bool = False
    waive_reason: str = ""

    def key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.symbol)

    def format(self) -> str:
        mark = " (waived)" if self.waived else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.symbol}] {self.message}{mark}"


def explain(rule_id: str) -> str:
    """Human ``--explain`` text for one rule (raises KeyError on unknown ids)."""
    r = RULES[rule_id]
    counter = r.counter if r.counter else "none — fails at trace time instead of counting"
    return (
        f"{r.id} ({r.family}): {r.summary}\n"
        f"\nobs counter: {counter}"
        f"\nruntime signal: {r.runtime_signal}\n"
        f"\n{r.rationale}\n"
        "\nWaiving: add {\"rule\": \"" + r.id + "\", \"path\": \"<repo-relative file>\","
        " \"symbol\": \"<symbol>\", \"reason\": \"<why this is safe>\"} to"
        " tmlint_baseline.json (see `python -m metrics_tpu.analysis --help`)."
    )
