"""Baseline (waiver) file handling.

``tmlint_baseline.json`` at the repo root records pre-existing findings that
were triaged and deliberately waived — each with a human reason. CI fails only
on findings NOT matched by the baseline, so the analyzer can land on a large
existing codebase and still guard every *new* line.

Waivers match on ``(rule, path, symbol)`` — not line numbers, so unrelated
edits don't churn the baseline. A waiver covers every finding with its key
(one symbol can produce several same-rule findings; they share one triage).
"""
import json
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from metrics_tpu.analysis.findings import Finding

BASELINE_FILENAME = "tmlint_baseline.json"


def load_baseline(path: str) -> Dict[Tuple[str, str, str], str]:
    """{(rule, path, symbol): reason} from a baseline file."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[Tuple[str, str, str], str] = {}
    for entry in data.get("waivers", []):
        reason = entry.get("reason", "")
        if not reason:
            raise ValueError(
                f"baseline waiver {entry.get('rule')}:{entry.get('path')}:{entry.get('symbol')}"
                " has no reason — every waiver must say why it is safe"
            )
        out[(entry["rule"], entry["path"], entry["symbol"])] = reason
    return out


def scope_waivers(
    waivers: Dict[Tuple[str, str, str], str], rules: Iterable[str]
) -> Dict[Tuple[str, str, str], str]:
    """Restrict a waiver table to the given rule ids.

    The baseline file is shared between the five analysis tiers — tmlint
    (TM-*), tmsan (TMS-*), tmrace (TMR-*), tmown (TMO-*), and tmshard
    (TMH-*). Each tier scopes the table to its own rule namespace before
    :func:`apply_baseline`, so a tier applies — and reports staleness for —
    only the waivers it can possibly match: a TMR-* waiver is never "stale"
    to a tmlint run that by construction emits no TMR findings, and vice
    versa. The scope sets (``LINT_RULES``, ``SAN_RULES``, ``RACE_RULES``,
    ``OWN_RULES``, ``SHARD_RULES`` in ``findings.py``) partition ``RULES``,
    so every waiver belongs to exactly one tier's staleness check.
    """
    allowed = set(rules)
    return {k: v for k, v in waivers.items() if k[0] in allowed}


def apply_baseline(
    findings: List[Finding], waivers: Dict[Tuple[str, str, str], str]
) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """Mark waived findings in place; returns (new_findings, unused_waiver_keys)."""
    used: Set[Tuple[str, str, str]] = set()
    new: List[Finding] = []
    for f in findings:
        reason = waivers.get(f.key())
        if reason is not None:
            f.waived = True
            f.waive_reason = reason
            used.add(f.key())
        else:
            new.append(f)
    unused = sorted(k for k in waivers if k not in used)
    return new, unused


def write_baseline(path: str, findings: Iterable[Finding], reason: str) -> int:
    """Write a baseline waiving every given finding with one shared reason.

    Meant for bootstrapping (``--write-baseline``); triaged per-finding reasons
    should then be edited in. Returns the number of waivers written.
    """
    seen: Set[Tuple[str, str, str]] = set()
    waivers = []
    for f in sorted(findings, key=lambda f: f.key()):
        if f.key() in seen:
            continue
        seen.add(f.key())
        waivers.append(
            {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "reason": f.waive_reason or reason,
            }
        )
    payload = {
        "version": 1,
        "comment": (
            "tmlint waivers: pre-existing findings triaged as safe. Matched on"
            " (rule, path, symbol); every entry needs a reason. See"
            " docs/source/pages/static_analysis.rst."
        ),
        "waivers": waivers,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    return len(waivers)


def default_baseline_path(repo_root: str) -> Optional[str]:
    cand = os.path.join(repo_root, BASELINE_FILENAME)
    return cand if os.path.exists(cand) else None
