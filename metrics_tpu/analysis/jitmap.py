"""The jit-boundary model: which code is reachable from a traced region.

Host-sync-shaped calls are only bugs when they can execute *under tracing*.
This module builds, per package, the set of (module, function) pairs reachable
from a jit entry, where entries are:

1. **decorators** — ``@jax.jit``, ``@partial(jax.jit, ...)``, ``@jax.vmap``,
   ``@jax.pmap``, ``@shard_map`` (anything that traces its target),
2. **call sites** — ``jax.jit(f)``, ``jax.vmap(f)``, ``jax.lax.scan(f, ...)``,
   ``lax.cond/while_loop/fori_loop/switch/associative_scan/map``,
   ``pl.pallas_call(kernel, ...)``, including targets wrapped in
   ``functools.partial`` and lambdas,
3. **the ``Metric._wrap_update`` entry** — every registered ``Metric``
   subclass's ``update``/``compute`` body (injected by the runner from
   import-time introspection; classes that declare ``_host_side_update = True``
   are host code by contract and are not entries),
4. **jit factories** — a local function whose *parameter* is called inside a
   jitted inner function (the ``_make_ovr(kernel)`` pattern in
   ops/clf_curve.py) marks the argument at each call site as an entry.

Reachability then propagates through the call graph — across modules of the
analyzed package via import resolution — but **only through trace-reachable
statements**: the repo's concreteness-guard idiom
(``if not _is_concrete(x): ...`` / ``isinstance(x, jax.core.Tracer)``,
metrics_tpu/utils/checks.py) partitions a function body into traced and
host-only regions, and calls made from host-only regions do not propagate.
This is what lets the exact-mode curve metrics keep their numpy compute path
(guarded, eager-only) without drowning the lint in false positives.
"""
import ast
import os
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: callables that trace their function argument(s)
_TRACING_WRAPPERS = {"jit", "pjit", "pmap", "vmap", "shard_map", "named_call", "checkpoint", "remat", "grad", "value_and_grad", "custom_jvp", "custom_vjp"}
#: jax.lax combinators: {name: positions of traced function args (None = all)}
_TRACING_COMBINATORS = {
    "scan": (0,),
    "cond": (1, 2),
    "while_loop": (0, 1),
    "fori_loop": (2,),
    "associative_scan": (0,),
    "map": (0,),
    "switch": None,
    "pallas_call": (0,),
    "custom_root": None,
    "custom_linear_solve": None,
}
#: functions recognized as concreteness guards (utils/checks.py idiom)
_CONCRETE_GUARDS = {"_is_concrete", "is_concrete"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminates(body: Sequence[ast.stmt]) -> bool:
    """Whether falling out of ``body`` is impossible (ends in return/raise/...)."""
    if not body:
        return False
    last = body[-1]
    if isinstance(last, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
        return True
    if isinstance(last, ast.If):
        return bool(last.orelse) and _terminates(last.body) and _terminates(last.orelse)
    return False


def tracing_truth(test: ast.expr) -> Optional[bool]:
    """Value of a guard expression *under tracing*: True/False when decidable.

    ``_is_concrete(...)`` is False under tracing; ``isinstance(x, ...Tracer)``
    is True. Boolean combinations fold through and/or/not; anything else is
    None (unknown).
    """
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        inner = tracing_truth(test.operand)
        return None if inner is None else (not inner)
    if isinstance(test, ast.BoolOp):
        vals = [tracing_truth(v) for v in test.values]
        if isinstance(test.op, ast.And):
            if any(v is False for v in vals):
                return False
            if all(v is True for v in vals):
                return True
        else:  # Or
            if any(v is True for v in vals):
                return True
            if all(v is False for v in vals):
                return False
        return None
    if isinstance(test, ast.Call):
        name = dotted_name(test.func)
        if name and name.split(".")[-1] in _CONCRETE_GUARDS:
            return False
        if name and name.split(".")[-1] == "isinstance" or (
            isinstance(test.func, ast.Name) and test.func.id == "isinstance"
        ):
            # isinstance(x, jax.core.Tracer) -> True under tracing
            if len(test.args) == 2:
                cls = dotted_name(test.args[1])
                if cls and cls.split(".")[-1] == "Tracer":
                    return True
        return None
    return None


def _has_guard(test: ast.expr) -> bool:
    """Whether the test mentions a concreteness guard at all (then the test
    expression itself must not be linted: its data-dependent sub-expressions
    only evaluate on the concrete side of a short-circuit)."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.split(".")[-1] in _CONCRETE_GUARDS:
                return True
            if name and name.split(".")[-1] == "isinstance" and len(node.args) == 2:
                cls = dotted_name(node.args[1])
                if cls and cls.split(".")[-1] == "Tracer":
                    return True
    return False


def iter_trace_regions(body: Sequence[ast.stmt], traced: bool = True) -> Iterable[Tuple[ast.stmt, bool, bool]]:
    """Yield ``(stmt, traced, lint_test)`` for every statement, guard-aware.

    ``traced`` is False for statements only reachable on the concrete (eager)
    side of a guard. ``lint_test`` is False for If/While statements whose test
    contains a guard call (the test short-circuits on concreteness and must
    not be linted). Nested function/class defs are NOT entered — they are
    separate symbols with their own reachability.
    """
    traced_now = traced
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            yield stmt, traced_now, True  # callers may want the def site itself
            continue
        if isinstance(stmt, ast.If):
            truth = tracing_truth(stmt.test)
            yield stmt, traced_now, not _has_guard(stmt.test)
            if truth is True:
                # tracing enters the body; orelse is eager-only
                yield from iter_trace_regions(stmt.body, traced_now)
                yield from iter_trace_regions(stmt.orelse, False)
                if _terminates(stmt.body):
                    traced_now = False  # the rest only runs eagerly
            elif truth is False:
                # tracing skips the body
                yield from iter_trace_regions(stmt.body, False)
                yield from iter_trace_regions(stmt.orelse, traced_now)
                if stmt.orelse and _terminates(stmt.orelse):
                    traced_now = False
            else:
                yield from iter_trace_regions(stmt.body, traced_now)
                yield from iter_trace_regions(stmt.orelse, traced_now)
            continue
        yield stmt, traced_now, True
        for sub in (
            getattr(stmt, "body", None),
            getattr(stmt, "orelse", None),
            getattr(stmt, "finalbody", None),
        ):
            if sub:
                yield from iter_trace_regions(sub, traced_now)
        for handler in getattr(stmt, "handlers", ()) or ():
            yield from iter_trace_regions(handler.body, traced_now)


@dataclass
class FuncInfo:
    qualname: str
    node: ast.AST  # FunctionDef | Lambda
    lineno: int
    cls: Optional[str] = None  # enclosing class name, if a method
    #: symbols called from trace-reachable statements (resolved in phase B)
    edges: Set[str] = field(default_factory=set)
    #: params that escape into a jitted inner region (jit-factory pattern)
    escaping_params: Set[str] = field(default_factory=set)


@dataclass
class JitAlias:
    """Module-level ``X = jax.jit(f, static_argnames=...)`` binding."""

    name: str
    target: Optional[str]  # qualname of the wrapped local function, if known
    static_argnames: Tuple[str, ...] = ()
    static_argnums: Tuple[int, ...] = ()
    lineno: int = 0


class ModuleModel:
    """Per-file AST model: functions, imports, jit entries, call edges."""

    def __init__(self, path: str, modname: str, source: str) -> None:
        self.path = path
        self.modname = modname
        self.tree = ast.parse(source)
        self.functions: Dict[str, FuncInfo] = {}
        self.imports: Dict[str, str] = {}  # local name -> "module" | "module:symbol"
        self.np_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.jit_aliases: Dict[str, JitAlias] = {}
        self.roots: Dict[str, str] = {}  # qualname -> reason
        #: local functions whose only call sites are module-level statements
        #: (setup/factory helpers run once at import; jit-in-body is fine there)
        self.module_level_only: Set[str] = set()
        self._collect()

    # ------------------------------------------------------------ phase A

    def _collect(self) -> None:
        self._walk_scope(self.tree.body, prefix="", cls=None, at_module_level=True)
        self._detect_factories()

    def _add_function(self, node: ast.AST, qualname: str, cls: Optional[str]) -> FuncInfo:
        info = FuncInfo(qualname=qualname, node=node, lineno=node.lineno, cls=cls)
        self.functions[qualname] = info
        return info

    def _walk_scope(self, body: Sequence[ast.stmt], prefix: str, cls: Optional[str], at_module_level: bool) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                self._record_import(stmt)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = prefix + stmt.name
                self._add_function(stmt, qual, cls)
                self._scan_decorators(stmt, qual)
                self._walk_scope(stmt.body, prefix=qual + ".", cls=cls, at_module_level=False)
            elif isinstance(stmt, ast.ClassDef):
                self._walk_scope(stmt.body, prefix=prefix + stmt.name + ".", cls=stmt.name, at_module_level=False)
            elif at_module_level and isinstance(stmt, ast.Assign):
                self._scan_module_assign(stmt)
        if at_module_level:
            # jit entries referenced from arbitrary module-level expressions
            for stmt in body:
                if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                    self._scan_calls_for_entries(stmt)

    def _record_import(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.Import):
            for alias in stmt.names:
                local = alias.asname or alias.name.split(".")[0]
                self.imports[local] = alias.name
                if alias.name == "numpy" or alias.name.startswith("numpy."):
                    self.np_aliases.add(local)
                if alias.name == "jax.numpy":
                    self.jnp_aliases.add(local)
        elif isinstance(stmt, ast.ImportFrom) and stmt.module:
            for alias in stmt.names:
                local = alias.asname or alias.name
                if stmt.module == "jax" and alias.name == "numpy":
                    self.jnp_aliases.add(local)
                    self.imports[local] = "jax.numpy"
                    continue
                if stmt.module == "numpy":
                    self.np_aliases.add(local)
                self.imports[local] = f"{stmt.module}:{alias.name}"

    # -- jit entry detection -------------------------------------------------

    def _is_tracing_wrapper(self, func: ast.expr) -> bool:
        name = dotted_name(func)
        if not name:
            return False
        last = name.split(".")[-1]
        if last not in _TRACING_WRAPPERS:
            return False
        # avoid false-positive on unrelated local symbols named e.g. `map`
        if "." in name:
            return True
        target = self.imports.get(name, "")
        return target.startswith("jax") or last in {"jit", "pjit", "pmap", "vmap", "shard_map"}

    def _combinator_positions(self, func: ast.expr) -> Optional[Tuple[Optional[Tuple[int, ...]], str]]:
        name = dotted_name(func)
        if not name:
            return None
        last = name.split(".")[-1]
        if last in _TRACING_COMBINATORS:
            return _TRACING_COMBINATORS[last], last
        return None

    def _mark_entry_expr(self, node: ast.expr, reason: str) -> None:
        """Mark the function referenced by an expression as a jit entry."""
        if isinstance(node, ast.Name):
            for qual, info in self.functions.items():
                if qual == node.id or qual.endswith("." + node.id):
                    self.roots.setdefault(qual, reason)
            return
        if isinstance(node, ast.Lambda):
            qual = f"<lambda@{node.lineno}>"
            if qual not in self.functions:
                self._add_function(node, qual, None)
            self.roots.setdefault(qual, reason)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name and name.split(".")[-1] == "partial":
                if node.args:
                    self._mark_entry_expr(node.args[0], reason)
                return
            # nested wrapper: jax.jit(jax.vmap(f))
            if self._is_tracing_wrapper(node.func) and node.args:
                self._mark_entry_expr(node.args[0], reason)
        if isinstance(node, ast.Attribute):
            name = dotted_name(node)
            if name and name.startswith("self."):
                # method references: self._kernel passed to vmap
                for qual in self.functions:
                    if qual.endswith("." + node.attr):
                        self.roots.setdefault(qual, "method passed to a tracing wrapper")

    def _scan_decorators(self, node: ast.AST, qual: str) -> None:
        for dec in getattr(node, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            if self._is_tracing_wrapper(target):
                self.roots.setdefault(qual, f"decorated by a tracing wrapper at line {dec.lineno}")
            elif isinstance(dec, ast.Call):
                name = dotted_name(dec.func)
                if name and name.split(".")[-1] == "partial" and dec.args:
                    if self._is_tracing_wrapper(dec.args[0]):
                        self.roots.setdefault(qual, f"decorated @partial(jit) at line {dec.lineno}")

    def _scan_calls_for_entries(self, root: ast.AST) -> None:
        for node in ast.walk(root):
            if not isinstance(node, ast.Call):
                continue
            if self._is_tracing_wrapper(node.func) and node.args:
                self._mark_entry_expr(node.args[0], f"passed to a tracing wrapper at line {node.lineno}")
                continue
            comb = self._combinator_positions(node.func)
            if comb is not None:
                positions, cname = comb
                args = node.args
                idxs = range(len(args)) if positions is None else [p for p in positions if p < len(args)]
                for i in idxs:
                    self._mark_entry_expr(args[i], f"traced by lax.{cname} at line {node.lineno}")

    def _scan_module_assign(self, stmt: ast.Assign) -> None:
        """Record ``X = jax.jit(f, static_argnames=(...))`` aliases."""
        if not (isinstance(stmt.value, ast.Call) and len(stmt.targets) == 1):
            return
        target = stmt.targets[0]
        if not isinstance(target, ast.Name):
            return
        call = stmt.value
        if not self._is_tracing_wrapper(call.func):
            return
        static_names: Tuple[str, ...] = ()
        static_nums: Tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg in ("static_argnames", "static_argnums"):
                vals: List = []
                for el in ast.walk(kw.value):
                    if isinstance(el, ast.Constant):
                        vals.append(el.value)
                if kw.arg == "static_argnames":
                    static_names = tuple(str(v) for v in vals)
                else:
                    static_nums = tuple(v for v in vals if isinstance(v, int))
        wrapped: Optional[str] = None
        if call.args:
            inner = call.args[0]
            if isinstance(inner, ast.Name) and inner.id in self.functions:
                wrapped = inner.id
            elif isinstance(inner, ast.Call):
                name = dotted_name(inner.func)
                if name and name.split(".")[-1] == "partial" and inner.args:
                    first = inner.args[0]
                    if isinstance(first, ast.Name) and first.id in self.functions:
                        wrapped = first.id
        self.jit_aliases[target.id] = JitAlias(
            name=target.id,
            target=wrapped,
            static_argnames=static_names,
            static_argnums=static_nums,
            lineno=stmt.lineno,
        )

    # -- jit factories -------------------------------------------------------

    def _detect_factories(self) -> None:
        """The ``_make_ovr(kernel)`` pattern: a param called inside a rooted
        inner function escapes into jit; call-site args at that position become
        entries. Also classifies which local functions are only ever called
        from module level (setup helpers — exempt from jit-in-body linting)."""
        for qual, info in self.functions.items():
            node = info.node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args]
            if not params:
                continue
            inner_rooted = [
                self.functions[q].node
                for q in self.roots
                if q.startswith(qual + ".") and q in self.functions
            ]
            called: Set[str] = set()
            for inner in inner_rooted:
                for sub in ast.walk(inner):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        called.add(sub.func.id)
            info.escaping_params = {p for p in params if p in called}

        # classify call sites: a function is a "setup helper" (jit-in-body is
        # fine — it runs once at import) only when it IS called at module level
        # and NOT from any function body. Never-called functions are runtime
        # API surface and stay lintable.
        called_from_funcs: Set[str] = set()
        for qual, info in self.functions.items():
            node = info.node
            body = node.body if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) else [ast.Expr(node.body)]
            for stmt in body:
                for sub in ast.walk(stmt):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        called_from_funcs.add(sub.func.id)
        called_at_module: Set[str] = set()
        for stmt in self.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                    called_at_module.add(sub.func.id)
        for qual in self.functions:
            base = qual.split(".")[-1]
            if base in called_at_module and base not in called_from_funcs:
                self.module_level_only.add(qual)

        # factory call sites at module level
        for stmt in self.tree.body:
            for node in ast.walk(stmt):
                if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)):
                    continue
                factory = self.functions.get(node.func.id)
                if factory is None or not factory.escaping_params:
                    continue
                fnode = factory.node
                if not isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                params = [a.arg for a in fnode.args.args]
                for i, arg in enumerate(node.args):
                    if i < len(params) and params[i] in factory.escaping_params:
                        self._mark_entry_expr(arg, f"escapes into jit via factory {factory.qualname} (line {node.lineno})")
                for kw in node.keywords:
                    if kw.arg in factory.escaping_params:
                        self._mark_entry_expr(kw.value, f"escapes into jit via factory {factory.qualname} (line {node.lineno})")

    # ------------------------------------------------------------ edges

    def collect_edges(self) -> None:
        """Record, per function, the symbols called from trace-reachable code."""
        for qual, info in self.functions.items():
            node = info.node
            if isinstance(node, ast.Lambda):
                stmts_flags: List[Tuple[ast.AST, bool]] = [(node.body, True)]
            else:
                stmts_flags = [
                    (stmt, traced)
                    for stmt, traced, _ in iter_trace_regions(node.body)
                    if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                ]
            for stmt, traced in stmts_flags:
                if not traced:
                    continue
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Call):
                        continue
                    if isinstance(sub.func, ast.Name):
                        info.edges.add(sub.func.id)
                    else:
                        name = dotted_name(sub.func)
                        if name:
                            info.edges.add(name)
            # entries passed onward as bare references (e.g. vmapped helpers)
            # are handled by the entry scan; method calls via self:
            info.edges = {e[5:] if e.startswith("self.") else e for e in info.edges}


# ---------------------------------------------------------------- package level


class PackageModel:
    """All ModuleModels of one analyzed tree + cross-module reachability."""

    def __init__(self, files: Dict[str, Tuple[str, str]]) -> None:
        """``files``: {repo_relative_path: (modname, source)}."""
        self.modules: Dict[str, ModuleModel] = {}
        self.errors: Dict[str, str] = {}
        for path, (modname, source) in files.items():
            try:
                self.modules[path] = ModuleModel(path, modname, source)
            except SyntaxError as err:  # lint must not die on one bad file
                self.errors[path] = f"SyntaxError: {err}"
        self.by_modname = {m.modname: m for m in self.modules.values()}
        for m in self.modules.values():
            m.collect_edges()
        #: (path, qualname) -> reason, filled by propagate()
        self.reachable: Dict[Tuple[str, str], str] = {}

    def inject_roots(self, extra: Dict[str, Dict[str, str]]) -> None:
        """``{repo_relative_path: {qualname: reason}}`` — introspection entries."""
        for path, quals in extra.items():
            module = self.modules.get(path)
            if module is None:
                continue
            for qual, reason in quals.items():
                if qual in module.functions:
                    module.roots.setdefault(qual, reason)
                else:
                    # tolerate minor qualname drift (nested class etc.): suffix match
                    for cand in module.functions:
                        if cand.endswith("." + qual) or cand.split(".", 1)[-1] == qual:
                            module.roots.setdefault(cand, reason)
                            break

    def _resolve(self, module: ModuleModel, symbol: str, cls: Optional[str]) -> Optional[Tuple[ModuleModel, str]]:
        """Resolve a called symbol to (module, qualname) within the package."""
        # method on the same class
        if cls is not None and f"{cls}.{symbol}" in module.functions:
            return module, f"{cls}.{symbol}"
        if symbol in module.functions:
            return module, symbol
        if symbol in module.jit_aliases:
            target = module.jit_aliases[symbol].target
            if target and target in module.functions:
                return module, target
            return None
        if "." in symbol:
            base, _, attr = symbol.partition(".")
            target_mod = module.imports.get(base)
            if target_mod:
                if ":" in target_mod:
                    # `from metrics_tpu.ops import rank as _rank` records
                    # "metrics_tpu.ops:rank" — the imported symbol may itself
                    # be a module of the analyzed package
                    m, _, nm = target_mod.partition(":")
                    sub = self.by_modname.get(f"{m}.{nm}")
                    if sub:
                        return self._resolve(sub, attr, None)
                    return None
                other = self.by_modname.get(target_mod)
                if other:
                    return self._resolve(other, attr, None)
            return None
        imported = module.imports.get(symbol)
        if imported and ":" in imported:
            modname, _, name = imported.partition(":")
            other = self.by_modname.get(modname)
            if other:
                return self._resolve(other, name, None)
            # `from metrics_tpu.ops import rank` style: symbol is a module
            sub = self.by_modname.get(f"{modname}.{name}")
            if sub is not None:
                return None
        return None

    def propagate(self) -> None:
        """BFS the call graph from all entries, trace-reachable edges only."""
        queue: List[Tuple[ModuleModel, str, str]] = []
        for module in self.modules.values():
            for qual, reason in module.roots.items():
                queue.append((module, qual, reason))
        while queue:
            module, qual, reason = queue.pop()
            key = (module.path, qual)
            if key in self.reachable:
                continue
            self.reachable[key] = reason
            info = module.functions.get(qual)
            if info is None:
                continue
            for edge in info.edges:
                resolved = self._resolve(module, edge, info.cls)
                if resolved is None:
                    continue
                tmod, tqual = resolved
                queue.append((tmod, tqual, f"called from {module.modname}:{qual}"))

    def reachable_functions(self) -> Iterable[Tuple[ModuleModel, FuncInfo, str]]:
        for (path, qual), reason in sorted(self.reachable.items()):
            module = self.modules[path]
            info = module.functions.get(qual)
            if info is not None:
                yield module, info, reason


def load_package(root: str, repo_root: str) -> Dict[str, Tuple[str, str]]:
    """Collect ``{repo_relative_path: (modname, source)}`` for a tree or file."""
    out: Dict[str, Tuple[str, str]] = {}
    paths: List[str] = []
    if os.path.isfile(root):
        paths = [root]
    else:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    paths.append(os.path.join(dirpath, fn))
    for path in paths:
        rel = os.path.relpath(os.path.abspath(path), repo_root).replace(os.sep, "/")
        mod = rel[:-3].replace("/", ".")
        if mod.endswith(".__init__"):
            mod = mod[: -len(".__init__")]
        with open(path, "r", encoding="utf-8") as fh:
            out[rel] = (mod, fh.read())
    return out
