"""tmshard — the static sharding & collective-correctness tier.

The fifth whole-package analysis tier (tmlint / tmsan / tmrace / tmown /
**tmshard**): an AST axis-and-placement model of the package's SPMD surface —
shard_map/pmap/vmap entries, collective sites, ``PartitionSpec``/
``NamedSharding`` placements, donating launches, executable-cache keys — with
a bound-axis-set must-fixpoint feeding six rules (TMH-*, findings.py) and the
``tmshard_state_plan.json`` worksheet ROADMAP items 1 & 4 design from.

Entry points: :func:`metrics_tpu.analysis.shard.runner.run_shard` and
``python -m metrics_tpu.analysis --shard [--write-plan]``.
"""
from metrics_tpu.analysis.shard.runner import ShardReport, run_shard  # noqa: F401
