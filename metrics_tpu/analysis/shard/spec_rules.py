"""tmshard policy: turn the linked axis/placement model into findings.

Five of the six rules read the model directly; TMH-MESH-DRIFT is the
item-1/item-4 analog of tmown's engine contract: a per-engine *mesh-awareness*
matrix over the four launch engines plus the shard_map serving program in
``parallel/mesh.py``, where a component absent from one engine while two or
more siblings have it is drift the unified engine (ROADMAP item 5) — or the
sharded-state design (item 1) — must resolve or deliberately exclude.
"""
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis.findings import Finding
from metrics_tpu.analysis.shard.axis_model import (
    _KEY_SHARD_RE, _REDUCE_PRIMS, ShardModel,
)

#: engine -> (repo-relative path, anchor qualname or None for whole-module).
#: fused/fleet/ingest/rank mirror tmown's launch anchors; ``mesh`` is the
#: shard_map serving program the matrix exists to compare them against.
ENGINES: Dict[str, Tuple[str, Optional[str]]] = {
    "fused": ("metrics_tpu/core/fused.py", "FusedCollectionUpdate._launch"),
    "fleet": ("metrics_tpu/core/fleet.py", "run_step"),
    "ingest": ("metrics_tpu/serve/ingest.py", "IngestQueue._launch_chain"),
    "rank": ("metrics_tpu/ops/clf_curve.py", None),
    "mesh": ("metrics_tpu/parallel/mesh.py", None),
}

#: matrix rows: component -> what counts as evidence (docs + --explain text)
COMPONENTS = (
    "axis_binding",      # enters a shard_map/pmap/vmap-with-axis context
    "collective_sync",   # issues psum/pmax/all_gather/pvary-family collectives
    "spec_plumbing",     # constructs PartitionSpec/NamedSharding specs
    "placed_io",         # places arrays (device_put+sharding) or reads .sharding
    "sharded_key_facet", # executable-cache key covers sharding/mesh/topology
    "topology_seed",     # derives work from process_topology/process identity
)


def dataflow_findings(model: ShardModel) -> List[Finding]:
    """TMH-AXIS-UNBOUND / SPEC-ALGEBRA / REPLICA-DIVERGE / DONATE-RESHARD /
    KEY-SHARD over every function of the linked model."""
    out: List[Finding] = []
    mapped_reach = model.mapped_reachable()

    for _m, func in model.all_functions():
        # ---- TMH-AXIS-UNBOUND: literal axes outside the must-bound set
        for site in func.collectives:
            if site.axes is None or not site.axes or func.bound is None:
                continue
            missing = site.axes - func.bound
            if not missing:
                continue
            via = f" (via {site.derived_from})" if site.derived_from else ""
            out.append(
                Finding(
                    rule="TMH-AXIS-UNBOUND", path=func.path, line=site.line,
                    col=site.col, symbol=func.qualname,
                    message=(
                        f"`{site.op}`{via} reduces over axis"
                        f" {sorted(missing)} but no mapped context reaching"
                        f" `{func.qualname}` binds it"
                        + (
                            f" (bound here: {sorted(func.bound)})"
                            if func.bound
                            else " (no shard_map/pmap reaches this function)"
                        )
                    ),
                )
            )
        # ---- TMH-SPEC-ALGEBRA: reduce of an operand partitioned on that axis
        if func.is_mapped_body:
            for site in func.collectives:
                if (
                    site.op in _REDUCE_PRIMS
                    and site.axes
                    and site.operand_param is not None
                ):
                    spec = func.in_spec_axes.get(site.operand_param)
                    if spec and (spec & site.axes):
                        shared = sorted(spec & site.axes)
                        out.append(
                            Finding(
                                rule="TMH-SPEC-ALGEBRA", path=func.path,
                                line=site.line, col=site.col,
                                symbol=func.qualname,
                                message=(
                                    f"`{site.op}` over axis {shared} of"
                                    f" `{site.operand_param}`, which the"
                                    f" in-spec *partitions* along {shared}:"
                                    " each shard holds distinct logical rows,"
                                    " so the cross-shard reduce mixes (psum:"
                                    " double-counts) them; reduce the local"
                                    " block first, then sync"
                                ),
                            )
                        )
        # ---- TMH-REPLICA-DIVERGE (a): host reads traced under a map
        if func.key() in mapped_reach:
            for line, col, name, kind in func.divergent_calls:
                out.append(
                    Finding(
                        rule="TMH-REPLICA-DIVERGE", path=func.path, line=line,
                        col=col, symbol=func.qualname,
                        message=(
                            f"`{name}` ({kind}) executes inside a mapped"
                            " trace: each replica bakes its own value into"
                            " the program, and any collective downstream"
                            " deadlocks or silently diverges; hoist the host"
                            " read into the eager launcher"
                        ),
                    )
                )
        # ---- TMH-REPLICA-DIVERGE (b): divergent value into a collective
        for site in func.collectives:
            tainted = sorted(site.operand_names & func.divergent_names)
            if tainted:
                out.append(
                    Finding(
                        rule="TMH-REPLICA-DIVERGE", path=func.path,
                        line=site.line, col=site.col, symbol=func.qualname,
                        message=(
                            f"`{site.op}` operand depends on"
                            f" {tainted}, assigned from a replica-divergent"
                            " host read; the collective combines different"
                            " values per replica (silent wrong result)"
                        ),
                    )
                )
        # ---- events (TMH-DONATE-RESHARD / TMH-KEY-SHARD)
        for ev in func.events:
            rule = {
                "donate_reshard": "TMH-DONATE-RESHARD",
                "key_shard": "TMH-KEY-SHARD",
            }[ev.kind]
            out.append(
                Finding(
                    rule=rule, path=ev.path, line=ev.line, col=ev.col,
                    symbol=ev.symbol, message=ev.detail,
                )
            )
    return out


# ------------------------------------------------------------ mesh contract


def extract_mesh_contract(
    model: ShardModel, engines: Optional[Dict[str, Tuple[str, Optional[str]]]] = None
) -> Dict[str, Dict]:
    """engine -> {path, anchor, components: {name: evidence | None}}."""
    matrix: Dict[str, Dict] = {}
    for engine, (path, anchor) in (engines or ENGINES).items():
        module = model.modules.get(path)
        if module is None:
            continue  # fixture runs analyze partial trees
        if anchor is not None and anchor not in module.functions:
            continue
        reach = model.reachable_from(module, anchor)
        comp: Dict[str, Optional[str]] = {c: None for c in COMPONENTS}
        has_cache = False
        for func in reach:
            if comp["axis_binding"] is None and (
                func.map_entries or func.is_mapped_body
            ):
                comp["axis_binding"] = func.qualname
            if comp["collective_sync"] is None and any(
                s.derived_from is None for s in func.collectives
            ):
                comp["collective_sync"] = func.qualname
            if comp["spec_plumbing"] is None and func.spec_ctors:
                comp["spec_plumbing"] = func.qualname
            if comp["placed_io"] is None and (
                func.device_puts or func.touches_sharding
            ):
                comp["placed_io"] = func.qualname
            if func.cache_get or func.cache_store:
                has_cache = True
                if comp["sharded_key_facet"] is None and any(
                    _KEY_SHARD_RE.search(field) for field in func.key_fields
                ):
                    comp["sharded_key_facet"] = func.qualname
            if comp["topology_seed"] is None:
                if any("process" in n.split(".")[-1] for _l, _c, n, _k in func.divergent_calls):
                    comp["topology_seed"] = func.qualname
                elif any(
                    fact.target_qual.split(".")[-1] == "process_topology"
                    for fact in func.calls
                ):
                    comp["topology_seed"] = func.qualname
        # a cache whose key functions read .sharding covers placement too
        if has_cache and comp["sharded_key_facet"] is None:
            for func in reach:
                if func.touches_sharding:
                    comp["sharded_key_facet"] = func.qualname
                    break
        anchor_line = 1
        anchor_func = module.functions.get(anchor) if anchor else None
        if anchor_func is not None:
            anchor_line = anchor_func.line
        matrix[engine] = {
            "path": path,
            "anchor": anchor,
            "anchor_line": anchor_line,
            "components": comp,
            "has_cache": has_cache,
        }
    return matrix


def drift_findings(matrix: Dict[str, Dict]) -> List[Finding]:
    """A component absent from one engine while >=2 siblings have it."""
    out: List[Finding] = []
    for comp in COMPONENTS:
        holders = [e for e, facts in matrix.items() if facts["components"][comp]]
        if len(holders) < 2:
            continue
        for engine, facts in matrix.items():
            if facts["components"][comp]:
                continue
            out.append(
                Finding(
                    rule="TMH-MESH-DRIFT", path=facts["path"],
                    line=facts["anchor_line"], col=0,
                    symbol=f"{engine}.{comp}",
                    message=(
                        f"engine `{engine}` lacks `{comp}` while"
                        f" {sorted(holders)} have it — the sharded-state /"
                        " pod-topology design (ROADMAP items 1 & 4) must add"
                        " it or record why this engine is exempt"
                    ),
                )
            )
    out.sort(key=lambda f: (f.path, f.symbol))
    return out
