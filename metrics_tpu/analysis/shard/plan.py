"""The shard-plan worksheet: the machine-readable input to ROADMAP items 1 & 4.

``python -m metrics_tpu.analysis --shard --write-plan`` regenerates the
checked-in ``tmshard_state_plan.json``: for every registered state of every
constructible Metric class (the tmlint ctor registry, the same sweep the
contract tests use), its reduction algebra, shape family, and the statically
derived legal shard axes — fleet-axis partitionable? psum-safe? cat-shard-only?
replicate-only? — each with a reason string, plus the per-engine mesh-contract
matrix from ``spec_rules.extract_mesh_contract``.  The
``test_plan_worksheet_in_sync`` test keeps the checked-in copy honest, exactly
like tmown's drift worksheet.

Verdict model (pure function of reduction x family x host-side contract):

- ``psum_safe``: sum/mean/max/min states are fixed-shape arithmetic reduces —
  one ``psum``/``pmean``/``pmax``/``pmin`` over a *replica* axis is exact.
- ``cat_shard_only``: cat states concatenate; they shard only by splitting
  rows (the per-host cat shards ckpt already writes), never by psum.
- ``fleet_partitionable``: sum/max/min states of a non-host-side class can
  live sharded ``P('fleet')`` — rows are independent streams and the fold
  algebra matches ``core/fleet.py``'s eligibility gate.  The cross-host sync
  must then reduce over a *data/host* axis, never the fleet axis itself (the
  TMH-SPEC-ALGEBRA double-count class).
- ``replicate_only``: None/callable reductions have no distributable algebra;
  state must stay replicated and merge through the host path.
"""
import json
import os
from typing import Any, Dict, Optional

PLAN_FILENAME = "tmshard_state_plan.json"

_COMMENT = (
    "Machine-extracted shard plan for every registered metric state: reduction"
    " algebra, shape family, and statically-derived legal shard axes, plus the"
    " per-engine mesh-awareness matrix. Regenerate with `python -m"
    " metrics_tpu.analysis --shard --write-plan`; consumed by ROADMAP items 1"
    " (P('fleet') sharded state) and 4 (pod-scale shard_map serving)."
)

_AXIS_LEGEND = {
    "psum_safe": (
        "state syncs with one fixed-shape arithmetic collective (psum/pmean/"
        "pmax/pmin) over a replica axis"
    ),
    "cat_shard_only": (
        "state concatenates: shard by splitting rows across hosts/devices"
        " (all_gather to merge), never by arithmetic reduce"
    ),
    "fleet_partitionable": (
        "rows are independent per-stream slots: legal to shard P('fleet')"
        " across the ICI mesh, syncing over a data/host axis only"
    ),
    "replicate_only": (
        "no distributable reduce algebra: keep replicated, merge on host"
    ),
}


def _reduction_repr(reduce_kind: Any) -> str:
    if reduce_kind is None:
        return "none"
    if isinstance(reduce_kind, str):
        return reduce_kind
    return "callable"


def _family_of(default: Any) -> str:
    if isinstance(default, list):
        return "cat_list"
    if type(default).__name__ == "CatBuffer":
        return "cat_buffer"
    ndim = getattr(default, "ndim", None)
    if ndim == 0:
        return "scalar"
    if ndim == 1:
        return "vector"
    if ndim == 2:
        return "matrix"
    return "tensor"


def _shape_of(default: Any):
    shape = getattr(default, "shape", None)
    if shape is not None:
        return list(shape)
    data = getattr(default, "data", None)
    if data is not None and hasattr(data, "shape"):
        return list(data.shape)
    return None


def _dtype_of(default: Any) -> Optional[str]:
    dtype = getattr(default, "dtype", None)
    if dtype is not None:
        return str(dtype)
    data = getattr(default, "data", None)
    if data is not None and hasattr(data, "dtype"):
        return str(data.dtype)
    return None


def state_verdicts(reduction: str, family: str, host_side: bool) -> Dict[str, Dict]:
    """The per-state shard verdicts (pure; unit-tested directly)."""
    is_cat = family in ("cat_list", "cat_buffer")
    psum_safe = reduction in ("sum", "mean", "max", "min") and not is_cat
    fleet_ok = reduction in ("sum", "max", "min") and not is_cat and not host_side
    replicate_only = not psum_safe and not is_cat

    verdicts = {
        "psum_safe": {
            "ok": psum_safe,
            "reason": (
                f"`{reduction}` reduce of a fixed-shape {family} state maps to"
                " one psum/pmean/pmax/pmin over the replica axis"
                if psum_safe
                else (
                    "cat states merge by concatenation (all_gather), an"
                    " arithmetic reduce would destroy rows"
                    if is_cat
                    else f"`{reduction}` reduction has no collective arithmetic"
                    " equivalent; syncing gathers + merges on each replica"
                )
            ),
        },
        "cat_shard_only": {
            "ok": is_cat,
            "reason": (
                "rows partition cleanly across hosts/devices; ckpt already"
                " writes per-host cat shards and re-reduces across topology"
                " change"
                if is_cat
                else f"{family} state is fixed-shape, row-splitting semantics"
                " do not apply"
            ),
        },
        "fleet_partitionable": {
            "ok": fleet_ok,
            "reason": (
                f"per-stream rows fold independently under `{reduction}` (the"
                " core/fleet.py eligibility algebra), so P('fleet') over the"
                " ICI mesh is legal — provided the cross-host sync reduces"
                " over a data/host axis, never the fleet axis itself (that is"
                " the TMH-SPEC-ALGEBRA double-count)"
                if fleet_ok
                else (
                    "fleet metrics cannot register cat state (no per-stream"
                    " segment fold)"
                    if is_cat
                    else (
                        "host-side update/compute contract: state transits the"
                        " host each step, a device-sharded table would thrash"
                        if host_side
                        else f"`{reduction}` is outside the fleet fold algebra"
                        " (sum/max/min)"
                    )
                )
            ),
        },
        "replicate_only": {
            "ok": replicate_only,
            "reason": (
                f"`{reduction}` reduction: keep replicated and merge through"
                " the host merge_state path"
                if replicate_only
                else "a distributable algebra exists (see the other verdicts)"
            ),
        },
    }
    return verdicts


def _plan_of(verdicts: Dict[str, Dict]) -> str:
    if verdicts["fleet_partitionable"]["ok"]:
        return "shard P('fleet'); sync over data/host axis"
    if verdicts["cat_shard_only"]["ok"]:
        return "shard rows per host/device; all_gather to merge"
    if verdicts["psum_safe"]["ok"]:
        return "replicate; one psum-family sync"
    return "replicate; host-path merge"


def worksheet(mesh_matrix: Dict[str, Dict]) -> Dict:
    """Build the full plan payload (imports the live registry: only the
    ``--write-plan`` path and the in-sync test pay the introspection cost)."""
    from metrics_tpu.analysis import registry

    classes: Dict[str, Dict] = {}
    skipped: Dict[str, str] = {}
    for item in list(registry.introspect_classes()) + list(
        registry.introspect_fleet_variants()
    ):
        if item.instance is None:
            skipped[item.name] = item.skip_reason
            continue
        inst = item.instance
        host_side = bool(getattr(type(inst), "_host_side_update", False))
        host_compute = bool(getattr(type(inst), "_host_side_compute", False))
        states: Dict[str, Dict] = {}
        for name in sorted(inst._reductions):
            reduction = _reduction_repr(inst._reductions[name])
            default = inst._defaults.get(name)
            family = _family_of(default)
            verdicts = state_verdicts(reduction, family, host_side)
            states[name] = {
                "reduction": reduction,
                "family": family,
                "shape": _shape_of(default),
                "dtype": _dtype_of(default),
                "persistent": bool(inst._persistent.get(name, False)),
                "verdicts": verdicts,
                "plan": _plan_of(verdicts),
            }
        classes[item.name] = {
            "host_side_update": host_side,
            "host_side_compute": host_compute,
            "fleet_size": getattr(inst, "fleet_size", None),
            "states": states,
        }
    return {
        "version": 1,
        "comment": _COMMENT,
        "axis_legend": _AXIS_LEGEND,
        "classes": {k: classes[k] for k in sorted(classes)},
        "skipped": {k: skipped[k] for k in sorted(skipped)},
        "engine_mesh_matrix": {
            k: mesh_matrix[k] for k in sorted(mesh_matrix)
        },
    }


def write_worksheet(path: str, payload: Dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=2, sort_keys=False)
        fh.write("\n")
    os.replace(tmp, path)


def load_worksheet(path: str) -> Dict:
    with open(path, "r", encoding="utf-8") as fh:
        return json.load(fh)
