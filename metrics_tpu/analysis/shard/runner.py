"""tmshard orchestration: parse -> link -> rules -> baseline -> report.

Pure host AST work — nothing imports or executes the analyzed modules (the
plan worksheet's introspection pass runs only under ``--write-plan`` and the
in-sync test), so the sweep is CI-safe on an accelerator-free box and costs
cold-start seconds (the ISSUE budget is <= 60 s; the package parses and
fixpoints in well under one).
"""
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from metrics_tpu.analysis import baseline as baseline_mod
from metrics_tpu.analysis.findings import SHARD_RULES, Finding
from metrics_tpu.analysis.jitmap import load_package
from metrics_tpu.analysis.runner import _find_repo_root
from metrics_tpu.analysis.shard import plan as plan_mod
from metrics_tpu.analysis.shard import spec_rules
from metrics_tpu.analysis.shard.axis_model import ShardModel, build_model


@dataclass
class ShardReport:
    """One tmshard run: the linked model plus rule output and baseline split."""

    findings: List[Finding] = field(default_factory=list)  # waived included
    new_findings: List[Finding] = field(default_factory=list)
    unused_waivers: List[Tuple[str, str, str]] = field(default_factory=list)
    parse_errors: Dict[str, str] = field(default_factory=dict)
    #: engine -> mesh-awareness matrix (the item 1/4 worksheet component)
    mesh_matrix: Dict[str, Dict] = field(default_factory=dict)
    stats: Dict[str, float] = field(default_factory=dict)
    model: Optional[ShardModel] = None

    @property
    def waived(self) -> List[Finding]:
        return [f for f in self.findings if f.waived]

    @property
    def exit_code(self) -> int:
        return 1 if self.new_findings else 0

    def plan_worksheet(self) -> Dict:
        return plan_mod.worksheet(self.mesh_matrix)


def _obs_inc(name: str, value: float = 1) -> None:
    from metrics_tpu.obs import registry as _obs

    if _obs._ENABLED:
        _obs.REGISTRY.inc("shard", name, value)


#: rule id -> obs counter suffix (mirrors Rule.counter in findings.py)
_RULE_COUNTERS = {
    "TMH-AXIS-UNBOUND": "axis_unbound",
    "TMH-SPEC-ALGEBRA": "spec_algebra",
    "TMH-REPLICA-DIVERGE": "replica_diverge",
    "TMH-DONATE-RESHARD": "donate_reshard",
    "TMH-KEY-SHARD": "key_shard",
    "TMH-MESH-DRIFT": "mesh_drift",
}


def run_shard(
    target: str = "metrics_tpu",
    baseline_path: Optional[str] = None,
    repo_root: Optional[str] = None,
) -> ShardReport:
    """Analyze ``target`` (package dir or single file) for sharding safety."""
    t0 = time.perf_counter()
    report = ShardReport()
    repo_root = repo_root or _find_repo_root(target)

    files = load_package(target, repo_root)
    model = build_model(files)
    report.model = model
    report.parse_errors = dict(model.errors)

    report.findings.extend(spec_rules.dataflow_findings(model))
    report.mesh_matrix = spec_rules.extract_mesh_contract(model)
    report.findings.extend(spec_rules.drift_findings(report.mesh_matrix))
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))

    if baseline_path is None:
        baseline_path = baseline_mod.default_baseline_path(repo_root)
    waivers = baseline_mod.load_baseline(baseline_path) if baseline_path else {}
    shard_waivers = baseline_mod.scope_waivers(waivers, SHARD_RULES)
    report.new_findings, report.unused_waivers = baseline_mod.apply_baseline(
        report.findings, shard_waivers
    )

    n_funcs = 0
    n_mapped = 0
    n_collectives = 0
    n_placements = 0
    for _m, func in model.all_functions():
        n_funcs += 1
        if func.is_mapped_body:
            n_mapped += 1
        n_collectives += sum(1 for s in func.collectives if s.derived_from is None)
        n_placements += len(func.placements)

    _obs_inc("findings", len(report.findings))
    for f in report.findings:
        suffix = _RULE_COUNTERS.get(f.rule)
        if suffix:
            _obs_inc(suffix)

    report.stats = {
        "files": len(model.modules),
        "functions": n_funcs,
        "mapped_bodies": n_mapped,
        "collectives": n_collectives,
        "placements": n_placements,
        "engines": len(report.mesh_matrix),
        "findings": len(report.findings),
        "waived": len(report.waived),
        "new": len(report.new_findings),
        "seconds": round(time.perf_counter() - t0, 3),
    }
    return report
